"""Register-file machine — a jittable KV with order-dependent semantics.

Second `ra_machine_xla`-contract machine family (after the commutative
CounterMachine): each lane replicates a fixed file of ``n_slots`` int32
registers supporting put / fetch-add / compare-and-set.  CAS makes the
fold **order-dependent**; cas-free windows still fold one-shot via
``jit_apply_batch`` (last-put + subsequent adds per slot — see the
method comment), cas windows take the in-order masked scan fallback.
The device analogue of the host KvMachine's cas counters, and the
shape of a metadata/config store replicated per cluster.

Encoding (command_spec int32[4]): ``[op, slot, value, expected]``
  op 0 = noop (term-opening entry)
  op 1 = put:  reg[slot] := value;                   reply old value
  op 2 = add:  reg[slot] += value;                   reply new value
  op 3 = cas:  if reg[slot] == expected: := value;   reply 1/0 (ok flag)

Reference parity: this is the ra-kv-store register workload folded
on-device; the host path (Machine.apply via JitMachine's bridge) gives
the same machine to classic RaServer deployments.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.machine import JitMachine


class RegisterMachine(JitMachine):
    command_spec = ("int32", (4,))
    reply_spec = ("int32", ())
    version = 0
    #: CAS does not commute — batch apply stays sound because
    #: jit_apply_batch folds the window IN ORDER (vectorized fast path
    #: for cas-free windows, masked sequential fold once a cas appears)
    supports_batch_apply = True

    def __init__(self, n_slots: int = 8) -> None:
        self.n_slots = n_slots

    def jit_init(self, n_lanes: int):
        return jnp.zeros((n_lanes, self.n_slots), jnp.int32)

    def jit_apply(self, meta, command, state):
        # command: [..., 4]; state: [..., S]
        op = command[..., 0]
        slot = jnp.clip(command[..., 1], 0, self.n_slots - 1)
        value = command[..., 2]
        expected = command[..., 3]
        current = jnp.take_along_axis(state, slot[..., None],
                                      axis=-1)[..., 0]
        cas_ok = (current == expected)
        new_val = jnp.where(
            op == 1, value,
            jnp.where(op == 2, current + value,
                      jnp.where((op == 3) & cas_ok, value, current)))
        write = (op == 1) | (op == 2) | ((op == 3) & cas_ok)
        # scatter the single-slot write (one-hot select: static shapes,
        # no dynamic-slice — vmap/scan friendly)
        onehot = (jnp.arange(self.n_slots) == slot[..., None])
        updated = jnp.where(onehot & write[..., None],
                            new_val[..., None], state)
        reply = jnp.where(op == 1, current,
                          jnp.where(op == 2, new_val,
                                    jnp.where(op == 3,
                                              cas_ok.astype(jnp.int32),
                                              0)))
        return updated, reply

    # -- one-shot window fold (engine batch path) --------------------------
    #
    # A window WITHOUT cas folds in one vectorized pass: the final value
    # of a slot is (value of its LAST put) + (sum of the adds AFTER that
    # put), or (current value + sum of all its adds) when no put landed.
    # With a small slot file the [..., S, A] masked sums are exact plain
    # int32 ops (int32 addition wraps identically to the sequential
    # fold), no matmul tricks needed.  Windows containing cas fall back
    # to JitMachine.sequential_window_fold under a lax.cond — cas reads
    # the evolving register, the one sequential dependency.  The engine
    # discards per-command replies on this path (lockstep.py step 5).

    def jit_apply_batch(self, meta, commands, mask, state):
        fast_ok = ~jnp.any(mask & (commands[..., 0] == 3))  # no cas
        return self.window_fold_dispatch(meta, commands, mask, state,
                                         fast_ok)

    def _batch_fast(self, commands, mask, state):
        """Vectorized cas-free window fold: last-put + subsequent adds."""
        S = self.n_slots
        A = commands.shape[-2]
        op = jnp.where(mask, commands[..., 0], 0)           # [..., A]
        slot = jnp.clip(commands[..., 1], 0, S - 1)         # jit_apply clips
        value = commands[..., 2]
        sr = jnp.arange(S)
        at_slot = slot[..., None, :] == sr[..., :, None]    # [..., S, A]
        hits_put = at_slot & (op == 1)[..., None, :]
        hits_add = at_slot & (op == 2)[..., None, :]
        pos = jnp.arange(A)
        lastput = jnp.max(jnp.where(hits_put, pos, -1), axis=-1)
        base_put = jnp.sum(
            jnp.where(hits_put & (pos == lastput[..., None]), value[..., None, :], 0),
            axis=-1)                                        # single selection
        base = jnp.where(lastput >= 0, base_put, state)
        adds_after = jnp.sum(
            jnp.where(hits_add & (pos > lastput[..., None]), value[..., None, :], 0),
            axis=-1)
        return base + adds_after

    def encode_command(self, command) -> jnp.ndarray:
        """Host commands: ("put", slot, v) | ("add", slot, v) |
        ("cas", slot, expected, new) | anything else -> noop.

        Malformed commands (wrong arity, non-int fields) also encode as
        noop rather than raising: this runs inside the replicated apply
        fold on EVERY member (core/server.py _apply_one), where an
        exception for one bad committed client input would crash the
        whole cluster's apply path."""
        try:
            if isinstance(command, tuple):
                if command[0] == "put" and len(command) == 3:
                    return jnp.asarray([1, int(command[1]),
                                        int(command[2]), 0], jnp.int32)
                if command[0] == "add" and len(command) == 3:
                    return jnp.asarray([2, int(command[1]),
                                        int(command[2]), 0], jnp.int32)
                if command[0] == "cas" and len(command) == 4:
                    return jnp.asarray([3, int(command[1]),
                                        int(command[3]),
                                        int(command[2])], jnp.int32)
        except (TypeError, ValueError, IndexError, OverflowError):
            # IndexError: empty tuple; OverflowError: out-of-int32 field
            pass
        return jnp.zeros((4,), jnp.int32)

    def decode_reply(self, reply) -> int:
        return int(reply)


def query_registers(state) -> list:
    """Query fun: the register file as a plain list (host path)."""
    import numpy as np
    return np.asarray(state).tolist()
