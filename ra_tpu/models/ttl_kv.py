"""TtlKvMachine — a Khepri-shaped tree/TTL store on the device apply path.

Khepri (the RabbitMQ metadata store built on ra) layers two things on a
plain KV machine: entries that EXPIRE and clients that WATCH keys.  This
machine is the lane-engine counterpart serving the ISSUE 20 read plane:
a fixed key space of ``n_keys`` cells per lane where every cell carries a
value, an absolute expiry deadline, and a watcher count — all dense
int32 arrays, so both the apply fold and the vectorized query kernel
stay shape-stable.

Time is LOGICAL: the machine's clock is the raft index of the last
applied command (``meta["index"]``), the one monotone counter every
replica already agrees on.  A ``put`` with ``ttl > 0`` stamps
``exp = clock + ttl``; expiry is LAZY — nothing sweeps the table, a
cell is simply absent once ``clock >= exp`` (reads and subsequent
writes observe the expiry; deterministic across replicas because the
clock is the log position, not wall time).  ``ttl <= 0`` means no
expiry (``exp = 0`` sentinel).

Absence is ``val == -1`` (stored values must be >= 0, as in jit_kv).

Command encoding (command_spec int32[4]): ``[op, key, value, ttl]``

  op 0 noop                 (term-opening entry)
  op 1 put(key, value, ttl) reply [1, old]       (old -1 if absent/expired)
  op 2 get(key)             reply [present, value]
  op 3 delete(key)          reply [present, old]
  op 4 watch(key)           reply [1, watchers]  (registration count)

Reply is int32[2].  A key outside [0, n_keys) degrades the command to a
no-op with reply [-2, -1].

Query encoding (query_spec int32[2]): ``[op, key]`` — the ISSUE 20
vectorized read path, evaluated at the serve watermark with NO log
append:

  op 0 size()         reply [n_live, clock]
  op 1 get(key)       reply [present, value]    (expired -> [0, -1])
  op 2 watchers(key)  reply [1, count]

Batch apply takes the universal in-order sequential fold (puts with
TTLs are index-dependent — each command's expiry stamps its OWN raft
index, so a last-writer-wins collapse would mis-stamp deadlines).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.machine import JitMachine

_I32 = jnp.int32


class TtlKvMachine(JitMachine):
    command_spec = ("int32", (4,))
    reply_spec = ("int32", (2,))
    query_spec = ("int32", (2,))
    query_reply_spec = ("int32", (2,))
    version = 0
    #: sound because the default jit_apply_batch IS the in-order masked
    #: sequential fold — no vectorized fast path (see module docstring)
    supports_batch_apply = True

    def __init__(self, n_keys: int = 64) -> None:
        self.n_keys = n_keys

    def jit_init(self, n_lanes: int):
        N, S = n_lanes, self.n_keys
        return {
            "vals": jnp.full((N, S), -1, _I32),   # -1 = absent
            "exp": jnp.zeros((N, S), _I32),       # 0 = never expires
            "watch": jnp.zeros((N, S), _I32),
            "clock": jnp.zeros((N,), _I32),       # last applied raft index
        }

    @staticmethod
    def _live(vals, exp, clock):
        # lazy expiry: a cell is live while unexpired (exp 0 = forever)
        return (vals >= 0) & ((exp == 0) | (exp > clock[..., None]))

    def jit_apply(self, meta, command, state):
        S = self.n_keys
        op = command[..., 0]
        raw_key = command[..., 1]
        value = command[..., 2]
        ttl = command[..., 3]
        key_ok = (raw_key >= 0) & (raw_key < S)
        key = jnp.clip(raw_key, 0, S - 1)

        clock = jnp.maximum(state["clock"], meta["index"].astype(_I32))
        vals, exp, watch = state["vals"], state["exp"], state["watch"]
        live = self._live(vals, exp, clock)

        cur = jnp.take_along_axis(vals, key[..., None], axis=-1)[..., 0]
        cur_live = jnp.take_along_axis(live, key[..., None],
                                       axis=-1)[..., 0]
        cur = jnp.where(cur_live, cur, -1)
        present = cur_live.astype(_I32)
        n_watch = jnp.take_along_axis(watch, key[..., None],
                                      axis=-1)[..., 0]

        val_bad = (op == 1) & (value < 0)
        put = (op == 1) & key_ok & ~val_bad
        dele = (op == 3) & key_ok
        wreg = (op == 4) & key_ok

        new_exp = jnp.where(ttl > 0, clock + ttl, 0)
        onehot = jnp.arange(S) == key[..., None]
        vals = jnp.where(onehot & put[..., None], value[..., None],
                         jnp.where(onehot & dele[..., None], -1, vals))
        exp = jnp.where(onehot & put[..., None], new_exp[..., None], exp)
        watch = watch + (onehot & wreg[..., None]).astype(_I32)

        code = jnp.where(put | wreg, 1,
                         jnp.where((op == 2) | dele, present, 0))
        val_out = jnp.where(wreg, n_watch + 1, cur)
        bad = ((op > 0) & ~key_ok) | val_bad
        code = jnp.where(bad, -2, code)
        reply = jnp.stack([code, jnp.where(bad, -1, val_out)], axis=-1)
        new_state = {"vals": vals, "exp": exp, "watch": watch,
                     "clock": clock}
        return new_state, reply

    # -- vectorized read path (ISSUE 20) -----------------------------------

    def jit_query(self, queries, state):
        # queries: [..., Kr, 2]; state arrays: vals/exp/watch [..., S],
        # clock [...] — pure gathers against the logical clock, no
        # state mutation (reads never enter the log, expiry stays lazy)
        S = self.n_keys
        op = queries[..., 0]
        raw_key = queries[..., 1]
        key_ok = (raw_key >= 0) & (raw_key < S)
        key = jnp.clip(raw_key, 0, S - 1)
        live = self._live(state["vals"], state["exp"],
                          state["clock"])                    # [..., S]
        val = jnp.take_along_axis(state["vals"][..., None, :],
                                  key[..., None], axis=-1)[..., 0]
        is_live = jnp.take_along_axis(live[..., None, :],
                                      key[..., None], axis=-1)[..., 0]
        n_w = jnp.take_along_axis(state["watch"][..., None, :],
                                  key[..., None], axis=-1)[..., 0]
        present = key_ok & is_live
        n_live = jnp.sum(live.astype(_I32), axis=-1)[..., None]
        code = jnp.where(op == 0, n_live,
                         jnp.where(op == 2, key_ok.astype(_I32),
                                   present.astype(_I32)))
        value = jnp.where(op == 0, state["clock"][..., None],
                          jnp.where(op == 2, jnp.where(key_ok, n_w, -1),
                                    jnp.where(present, val, -1)))
        return jnp.stack([code, value], axis=-1)

    # -- host protocol -----------------------------------------------------

    def encode_command(self, command):
        try:
            if isinstance(command, tuple) and command:
                kind = command[0]
                if kind == "put" and len(command) in (3, 4):
                    ttl = int(command[3]) if len(command) == 4 else 0
                    return jnp.asarray(
                        [1, int(command[1]), int(command[2]), ttl], _I32)
                if kind == "get" and len(command) == 2:
                    return jnp.asarray([2, int(command[1]), 0, 0], _I32)
                if kind == "delete" and len(command) == 2:
                    return jnp.asarray([3, int(command[1]), 0, 0], _I32)
                if kind == "watch" and len(command) == 2:
                    return jnp.asarray([4, int(command[1]), 0, 0], _I32)
        except (TypeError, ValueError, OverflowError):
            pass
        return jnp.zeros((4,), _I32)

    def decode_reply(self, reply):
        code, val = int(reply[..., 0]), int(reply[..., 1])
        return (code, None if val < 0 else val)

    def encode_query(self, query):
        try:
            if isinstance(query, tuple) and query:
                kind = query[0]
                if kind == "get" and len(query) == 2:
                    return jnp.asarray([1, int(query[1])], _I32)
                if kind == "watchers" and len(query) == 2:
                    return jnp.asarray([2, int(query[1])], _I32)
        except (TypeError, ValueError, OverflowError):
            pass
        return jnp.zeros((2,), _I32)  # size()

    def decode_query_reply(self, reply):
        code, val = int(reply[..., 0]), int(reply[..., 1])
        return (code, None if val < 0 else val)


def query_live(state) -> dict:
    """Query fun: live (unexpired) keys as a plain dict (host path)."""
    import numpy as np
    vals = np.asarray(state["vals"])
    exp = np.asarray(state["exp"])
    clock = int(np.asarray(state["clock"]))
    out = {}
    for k, (v, e) in enumerate(zip(vals, exp)):
        if v >= 0 and (e == 0 or e > clock):
            out[int(k)] = int(v)
    return out
