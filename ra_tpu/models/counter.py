"""Counter machine — the simplest jittable state machine.

The TPU-native analogue of wrapping ``erlang:'+'/2`` in ra_machine_simple
(the machine ra_bench uses, /root/reference/src/ra_bench.erl:43-49): state
is one int64 per lane-member, a command is one int32 increment, the reply
is the new value.  Payload 0 encodes a noop (the term-opening entry), so
the engine's election path composes with it for free.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.machine import JitMachine


class CounterMachine(JitMachine):
    command_spec = ("int32", (1,))
    reply_spec = ("int32", ())
    version = 0

    def jit_init(self, n_lanes: int):
        return jnp.zeros((n_lanes,), jnp.int32)

    supports_batch_apply = True

    def jit_apply(self, meta, command, state):
        # command: [..., 1] int32; state: [...] int32
        inc = command[..., 0]
        new_state = state + inc
        return new_state, new_state

    def jit_apply_batch(self, meta, commands, mask, state):
        # commands: [..., A, 1]; mask: [..., A] — addition commutes, so a
        # whole committed window folds in one masked sum
        inc = jnp.sum(jnp.where(mask, commands[..., 0], 0), axis=-1)
        return state + inc

    def encode_command(self, command):
        return jnp.asarray([int(command)], jnp.int32)

    def decode_reply(self, reply):
        return int(reply)

    # -- vectorized read path (ISSUE 20) -----------------------------------

    query_spec = ("int32", (1,))
    query_reply_spec = ("int32", (1,))

    def jit_query(self, queries, state):
        # queries: [..., Kr, 1] (payload ignored); state: [...] int32 —
        # every query answers the counter value at the serve watermark
        Kr = queries.shape[-2]
        return jnp.broadcast_to(state[..., None, None],
                                state.shape + (Kr, 1))

    def encode_query(self, query):
        return jnp.zeros((1,), jnp.int32)

    def decode_query_reply(self, reply):
        return int(reply[..., 0])
