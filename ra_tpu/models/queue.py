"""Minimal queue machine — the ra_queue.erl test fixture equivalent.

The reference keeps a deliberately tiny queue machine (test/ra_queue.erl)
next to the full ra_fifo: state is a list of pending items; ``enq`` adds,
``deq`` pops and sends the item to a pid as a send_msg effect.  Used by
the nemesis/partition tests where the workload must be easy to reason
about while still exercising SendMsg effects and state replication.

Commands:  ("enq", item)            -> reply "ok"
           ("deq", pid)             -> pops head, SendMsg(pid, ("item", x))
           ("deq",)                 -> pops head, reply ("item", x)
"""
from __future__ import annotations

from collections import deque
from typing import Any

from ..core.machine import ApplyMeta, Machine
from ..core.types import SendMsg


class QueueMachine(Machine):
    version = 0

    def init(self, config: dict) -> deque:
        return deque()

    def apply(self, meta: ApplyMeta, command: Any, state: deque):
        kind = command[0]
        if kind == "enq":
            state.append((meta.index, command[1]))
            return state, "ok"
        if kind == "deq":
            if not state:
                return state, "empty"
            _idx, item = state.popleft()
            if len(command) > 1 and command[1] is not None:
                return state, "ok", [SendMsg(command[1], ("item", item))]
            return state, ("item", item)
        return state, ("error", "unknown_command")

    def overview(self, state: deque) -> dict:
        return {"type": "queue", "len": len(state)}
