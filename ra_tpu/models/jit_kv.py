"""JitKvMachine — the replicated KV store on the device apply path.

The host :class:`~ra_tpu.models.kv.KvMachine` (the ra-kv-store role,
README.md:33-35) keeps a Python dict plus watcher effects.  This is its
TPU-native counterpart for the BASELINE.md "2,000 clusters, kv machine,
mixed put/get, jittable apply/3" row: a fixed key space of ``n_keys``
int32 cells per lane, folded on-device under ``lax.scan`` (put/cas
sequences are order-dependent; cas-free windows still fold one-shot
via ``jit_apply_batch`` — see the method comment).

Absence is encoded as -1 (mirroring the host machine's ``None`` reply for
a missing key), so stored values must be >= 0.  ``get`` exists as a
committed command — a linearizable read through the log, the device-path
stand-in for ``consistent_query`` — while the host path keeps using query
funs.

Command encoding (command_spec int32[4]): ``[op, key, value, expected]``

  op 0 noop
  op 1 put(key, value)            reply [1, old]         (old -1 if absent)
  op 2 get(key)                   reply [present, value]
  op 3 delete(key)                reply [present, old]
  op 4 cas(key, expected, value)  reply [ok, current]    (expected/value -1
                                   mean absent: expect-missing / delete-on-
                                   success, matching KvMachine's None args)

Reply is int32[2] = [code, value].  A key outside [0, n_keys) makes the
command a no-op with reply [-2, -1] (never aliased onto a boundary cell).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.machine import JitMachine
from ..ops.exact import place16

_I32 = jnp.int32


class JitKvMachine(JitMachine):
    command_spec = ("int32", (4,))
    reply_spec = ("int32", (2,))
    version = 0
    #: put/cas do not commute — batch apply stays sound because
    #: jit_apply_batch folds the window IN ORDER (last-writer-wins
    #: vectorized fast path for cas-free windows, masked scan else)
    supports_batch_apply = True

    def __init__(self, n_keys: int = 64) -> None:
        self.n_keys = n_keys

    def jit_init(self, n_lanes: int):
        # -1 = absent
        return jnp.full((n_lanes, self.n_keys), -1, _I32)

    def jit_apply(self, meta, command, state):
        S = self.n_keys
        op = command[..., 0]
        raw_key = command[..., 1]
        key_ok = (raw_key >= 0) & (raw_key < S)
        key = jnp.clip(raw_key, 0, S - 1)
        value = command[..., 2]
        expected = command[..., 3]
        cur = jnp.take_along_axis(state, key[..., None], axis=-1)[..., 0]
        present = (cur >= 0).astype(_I32)

        # an out-of-range key must not alias onto the boundary cell, and a
        # negative value must not smuggle the absent sentinel into a cell
        # (stored values are >= 0 by contract; cas value -1 is the
        # intentional delete-on-success, anything below is malformed):
        # either way the command degrades to a no-op with the error reply
        val_bad = ((op == 1) & (value < 0)) | ((op == 4) & (value < -1))
        put = (op == 1) & key_ok & ~val_bad
        dele = (op == 3) & key_ok
        cas_ok = (op == 4) & key_ok & ~val_bad & (cur == expected)
        new_val = jnp.where(put, value,
                            jnp.where(dele, -1,
                                      jnp.where(cas_ok, value, cur)))
        write = put | dele | cas_ok
        onehot = (jnp.arange(S) == key[..., None])
        new_state = jnp.where(onehot & write[..., None],
                              new_val[..., None], state)

        code = jnp.where(put, 1,
                         jnp.where(op == 4, cas_ok.astype(_I32),
                                   jnp.where((op == 2) | dele, present, 0)))
        bad = ((op > 0) & ~key_ok) | val_bad
        code = jnp.where(bad, -2, code)
        reply = jnp.stack([code, jnp.where(bad, -1, cur)], axis=-1)
        return new_state, reply

    # -- one-shot window fold (engine batch path) --------------------------
    #
    # put/cas do not commute, but a window WITHOUT cas folds in one
    # vectorized pass: gets read, puts/deletes write, and the final cell
    # value is simply the LAST write targeting that key — last-writer-
    # wins needs no sequential fold.  Per key: the winning command is
    # the max window position among its writes (a masked max-reduce),
    # and its value lands via the exact split16 one-hot matmul (ops/exact.py) so placement rides the MXU
    # instead of a scatter.  Windows containing cas fall back to an
    # in-order masked lax.scan of jit_apply — cas reads the evolving
    # cell, the one true sequential dependency in the vocabulary.
    # The engine discards per-command replies on this path
    # (lockstep.py step 5), so the fold only produces the new state.

    def jit_apply_batch(self, meta, commands, mask, state):
        fast_ok = ~jnp.any(mask & (commands[..., 0] >= 4))  # no cas
        return self.window_fold_dispatch(meta, commands, mask, state,
                                         fast_ok)

    def _batch_fast(self, commands, mask, state):
        """Vectorized cas-free window fold: last write per key wins."""
        S = self.n_keys
        A = commands.shape[-2]
        op = jnp.where(mask, commands[..., 0], 0)           # [..., A]
        raw_key = commands[..., 1]
        value = commands[..., 2]
        key_ok = (raw_key >= 0) & (raw_key < S)
        val_bad = (op == 1) & (value < 0)
        is_write = ((op == 1) | (op == 3)) & key_ok & ~val_bad
        wval = jnp.where(op == 1, value, -1)                # delete = -1

        kr = jnp.arange(S)
        hits = (raw_key[..., None, :] == kr[..., :, None]) & \
            is_write[..., None, :]                          # [..., S, A]
        pos = jnp.arange(A)
        maxpos = jnp.max(jnp.where(hits, pos, -1), axis=-1)  # [..., S]
        winner = hits & (pos == maxpos[..., None])
        placed = place16(winner.astype(jnp.float32), wval)
        return jnp.where(maxpos >= 0, placed, state)

    # -- host protocol -----------------------------------------------------

    def encode_command(self, command):
        def _v(x):
            return -1 if x is None else int(x)
        try:
            if isinstance(command, tuple) and command:
                kind = command[0]
                if kind == "put" and len(command) == 3:
                    return jnp.asarray(
                        [1, int(command[1]), _v(command[2]), 0], _I32)
                if kind == "get" and len(command) == 2:
                    return jnp.asarray([2, int(command[1]), 0, 0], _I32)
                if kind == "delete" and len(command) == 2:
                    return jnp.asarray([3, int(command[1]), 0, 0], _I32)
                if kind == "cas" and len(command) == 4:
                    # host order: ("cas", key, expected, new)
                    return jnp.asarray(
                        [4, int(command[1]), _v(command[3]),
                         _v(command[2])], _I32)
        except (TypeError, ValueError, OverflowError):
            pass
        return jnp.zeros((4,), _I32)

    def decode_reply(self, reply):
        code, val = int(reply[..., 0]), int(reply[..., 1])
        return (code, None if val < 0 else val)

    # -- vectorized read path (ISSUE 20) -----------------------------------
    # Query encoding (query_spec int32[2]): ``[op, key]``
    #   op 0 size()    reply [n_present, 0]
    #   op 1 get(key)  reply [present, value]   (absent/bad key -> [0,-1])

    query_spec = ("int32", (2,))
    query_reply_spec = ("int32", (2,))

    def jit_query(self, queries, state):
        # queries: [..., Kr, 2]; state: [..., S] — pure gathers, no
        # state mutation (reads never enter the log)
        S = self.n_keys
        op = queries[..., 0]
        raw_key = queries[..., 1]
        key_ok = (raw_key >= 0) & (raw_key < S)
        key = jnp.clip(raw_key, 0, S - 1)
        val = jnp.take_along_axis(state[..., None, :],
                                  key[..., None], axis=-1)[..., 0]
        present = key_ok & (val >= 0)
        size = jnp.sum((state >= 0).astype(_I32),
                       axis=-1)[..., None]                    # [..., 1]
        code = jnp.where(op == 0, size, present.astype(_I32))
        value = jnp.where(op == 0, 0, jnp.where(present, val, -1))
        return jnp.stack([code, value], axis=-1)

    def encode_query(self, query):
        if isinstance(query, tuple) and query and query[0] == "get":
            return jnp.asarray([1, int(query[1])], _I32)
        return jnp.zeros((2,), _I32)  # size()

    def decode_query_reply(self, reply):
        code, val = int(reply[..., 0]), int(reply[..., 1])
        return (code, None if val < 0 else val)


def query_kv(state) -> dict:
    """Query fun: present keys as a plain dict (host path)."""
    import numpy as np
    arr = np.asarray(state)
    return {int(k): int(v) for k, v in enumerate(arr) if v >= 0}
