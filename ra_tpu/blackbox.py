"""Black-box flight recorder + the causal-event registry (ISSUE 7).

PR 6's Observatory answers *what* is slow (aggregate counters,
histograms, top-K offenders); this module answers *why a specific
command took 191ms* and *what the system was doing when it died*:

* :class:`FlightRecorder` — an always-on, bounded, per-subsystem
  structured-event ring.  Every plane (engine dispatch, WAL shards,
  reliable RPC, supervisors, fault plans, nemesis) emits typed events
  through :func:`record`; the emit path is one dict lookup + one deque
  append (no locks, no host syncs — lint rule RA04 gates it like the
  telemetry sampler's tick path).  The ring is the aircraft black box:
  it records continuously and is only *read* when something crashes.
* **Post-mortem bundles** — on supervisor escalation, poisoned-WAL
  rollover, ``MAX_POISON_STREAK`` thread death, a nemesis kill, or an
  unhandled server crash, :meth:`FlightRecorder.dump` writes one JSON
  bundle: the recent event rings + every registered state source
  (Observatory snapshot, per-shard WAL watermarks, active FaultPlan /
  DiskFaultPlan state, durability config).  Recovery later stamps a
  join-able report next to the bundle (:func:`stamp_recovery`), so a
  crash and the recovery that answered it read as one incident.
* :data:`EVENT_REGISTRY` — the central event-type registry.  Lint rule
  RA06 (tools/lint.py) statically requires every event type emitted
  anywhere (``record(...)``/``trace.span(...)``/``trace.instant(...)``)
  to be a key here and documented in docs/OBSERVABILITY.md — the
  RA05 field-registry discipline applied to events; the runtime mirror
  is the ``unregistered_events`` self-counter (MUST stay 0).

Trace-context joins: host-side events carry either an explicit
``trace`` id (classic commands: the context rides the command object
and the RPC frames) or a join key — ``(uid, idx)`` for the WAL plane,
``(lane, submit_index)``/``step`` for the engine plane, where commands
are never tagged inside jit (the dispatch loop stays host-sync-free;
see docs/INTERNALS.md §10 for the step-stamp join).
``tools/ra_trace.py`` reconstructs per-command timelines from bundles.
"""
from __future__ import annotations

import collections
import json
import logging
import os
import tempfile
import threading
import time
from typing import Any, Callable, Optional

logger = logging.getLogger("ra_tpu.blackbox")

#: every event type the tracing/flight-recorder plane may emit, with a
#: one-line meaning (the machine-checked registry; RA06 gates emit
#: sites against the KEYS, docs/OBSERVABILITY.md documents them).
#: Span names recorded through ra_tpu.trace at module level are events
#: too — a Chrome trace and a post-mortem bundle must speak one
#: vocabulary.
EVENT_REGISTRY = {
    # -- command lifecycle (classic path; `trace` = propagated ctx) ----
    "cmd.ingress": "client created a trace context at the API boundary",
    "cmd.submit": "traced command handed to a member (one per attempt; "
                  "redirects show as extra submits)",
    "cmd.append": "leader appended the command at (uid, idx, term)",
    "cmd.commit": "a server's commit index advanced to idx (uid-keyed)",
    "cmd.apply": "a traced command was applied on a member",
    # -- classic replication batching (ISSUE 13) -----------------------
    "rpc.batch": "leader built one multi-entry AppendEntries batch "
                 "(entry count + payload bytes; ONE event per batch, "
                 "never per entry)",
    # -- reliable control-plane RPC (transport/rpc.py) -----------------
    "rpc.send": "reliable-RPC attempt left the sender (rid stable "
                "across retries)",
    "rpc.recv": "receiver started executing a request id",
    "rpc.dup": "receiver dedup hit — duplicate delivery of a seen rid "
               "under the same trace context",
    "rpc.expired": "request arrived past its propagated deadline",
    # -- transport fault plan ------------------------------------------
    "net.fault": "transport FaultPlan injected a fault (kind, peer, "
                 "frame class)",
    "rpc.domain_delay": "latency-domain matrix stretched a frame "
                        "crossing (src -> dst) domains (ISSUE 19: "
                        "geography, not chaos — rides the same "
                        "per-(peer, class, direction) streams)",
    # -- WAL plane (per shard) -----------------------------------------
    "wal.batch": "span: one group-commit batch (write + sync + notify)",
    "wal.write": "one group-commit batch reached the file (per-uid "
                 "index ranges ride along)",
    "wal.fsync": "durability syscall latency (ms)",
    "wal.confirm": "per-writer durable range notify (uid, lo..hi)",
    "wal.resend": "out-of-sequence write gap -> resend_from signal",
    "wal.poison": "batch I/O error poisoned the current WAL file",
    "wal.escalate": "poison streak exhausted -> thread death "
                    "(supervisor restart)",
    "wal.kill": "injected WAL crash (nemesis / kill hook)",
    "wal.restart": "supervised restart of a dead WAL incarnation",
    # -- engine durability bridge (keyed by step = submit_index) -------
    "engine.step": "span: one single-step XLA dispatch",
    "engine.superstep": "span: one fused K-round XLA dispatch",
    "engine.backpressure": "span: dispatch thread waiting on the "
                           "unconfirmed-step window",
    "engine.wal_submit": "span: handing a dispatch's aux to the WAL "
                         "shards",
    "wal.encode": "span: shard encode worker pulled+encoded one "
                  "step's WAL block",
    "engine.submit": "dispatch queued steps [step_lo, step_hi] to "
                     "every WAL shard",
    "engine.confirm": "a shard's durable step horizon advanced",
    "engine.crash": "a shard encode worker died on an exception",
    "engine.elect": "host requested elections for a lane set",
    "engine.fail": "host failure detector marked a member down",
    "engine.recover": "host revived a member via snapshot install",
    "engine.member": "host membership change (add/promote/remove)",
    # -- storage fault plan --------------------------------------------
    "disk.fault": "DiskFaultPlan injected a fault (kind, path class, "
                  "op, path)",
    # -- supervision / crashes -----------------------------------------
    "sup.restart": "a supervisor restarted a dead component",
    "sup.giveup": "restart intensity exceeded; supervisor backing off",
    "srv.crash": "a server shell crashed out of the node event loop",
    # -- nemesis -------------------------------------------------------
    "nemesis.op": "chaos schedule executed one op",
    # -- SLO autotuner (ra_tpu/autotune.py, ISSUE 9) -------------------
    "tune.decision": "autotuner changed a knob (knob, old->new, "
                     "triggering phase + objective) — RA07: no silent "
                     "knob turns",
    "tune.freeze": "autotuner entered a freeze (active FaultPlan/"
                   "DiskFaultPlan or a fresh incident): decisions "
                   "suspended",
    # -- ingress plane (ra_tpu/ingress/, ISSUE 10) ---------------------
    "ingress.connect": "session (re)connected: epoch bump under a "
                       "stable (tenant, lane, shard) placement",
    "ingress.level": "backpressure ladder level transition "
                     "(SLO-verdict-driven; open/tight/fair)",
    "ingress.shed": "coalescer ring overflow began shedding rows "
                    "(transition into a shed episode, not per row)",
    # -- read lane (ra_tpu/ingress/, ISSUE 20) -------------------------
    "read.shed": "ladder bias began shedding read waves at admission "
                 "(any tightened level refuses reads BEFORE writes "
                 "are delayed; transition, not per row)",
    "read.stale": "the device refused pending reads rather than serve "
                  "past lease/quorum cover (stale-refusal episode "
                  "transition — the linearizable-read oracle pins "
                  "stale SERVES, refusals are the safe outcome)",
    # -- wire plane (ra_tpu/wire/, ISSUE 12) ---------------------------
    "wire.conn": "connection lifecycle: accept/close/bulk-connect/"
                 "reconnect-storm (loopback fleets emit ONE event, "
                 "never one per connection)",
    "wire.credit": "the credit-frame ladder level changed between "
                   "sweeps (transition only, never per row)",
    "wire.shed": "a sweep began answering shed verdicts (transition "
                 "into a wire shed episode)",
    "wire.error": "protocol error (bad hello/version/record) closed "
                  "a connection",
    # -- device plane (ra_tpu/devicewatch.py, ISSUE 16) ----------------
    "device.recompile": "recompile sentinel caught a steady-state "
                        "retrace of a wrapped jit entry point (fn tag "
                        "+ which argument's shape/dtype/sharding "
                        "drifted + compile wall ms)",
    "profile.captured": "a jax_profile() capture finished; the profile "
                        "dir rides along so the capture shows up in "
                        "ra_trace timelines instead of being a side "
                        "file nobody finds",
    # -- engine failure detector (supervisor tier, ISSUE 17) -----------
    "detector.suspect": "failure detector escalated a peer/engine to "
                        "suspect (silent beyond suspect_after; age = "
                        "seconds since last heard)",
    "detector.down": "failure detector confirmed a peer/engine down "
                     "(silent beyond down_after AND suspect for the "
                     "full hysteresis window; age rides along)",
    # -- placement failover (ISSUE 17; `trace` = migrated-cmd ctx) -----
    "placement.refuse": "a lane range's old home refused/was "
                        "unreachable for a session (the client-visible "
                        "start of a failover incident)",
    "placement.migrate": "control plane committed a lane-range "
                         "re-placement through the placement table "
                         "(rid, victim -> survivor, new generation)",
    "placement.adopt": "survivor restored a victim engine's durable "
                       "lane state (checkpoint + WAL-shard merge, "
                       "gated at the fsynced watermark)",
    "placement.rehome": "sessions re-bound to the new home: epoch "
                        "bump, dedup slots claimed, ack watermarks "
                        "re-seeded",
    "placement.giveup": "a bounded placement retry loop exhausted its "
                        "deadline/attempts and gave up (RA16: no "
                        "silent infinite retry in the control plane)",
    # -- cross-host placement serving path (ISSUE 19) ------------------
    "placement.rehome_hint": "listener refused a frame routed on a "
                             "stale placement revision with a typed "
                             "REHOME hint (engine, generation, rev) — "
                             "never a silent misroute into a dead "
                             "engine's lanes",
    "placement.adopt_rpc": "a survivor host committed an adoption "
                           "requested over the reliable control-plane "
                           "RPC tier (host_adopt — retried, "
                           "deduplicated, deadline-bounded)",
    "placement.stale_probe": "supervisor discarded a probe reply from "
                             "a superseded engine generation (a stale "
                             "reply must not reset the new incumbent's "
                             "suspect streak)",
    # -- recorder meta -------------------------------------------------
    "bb.dump": "post-mortem bundle written",
    "bb.recover": "recovery stamped a join-able recovery report",
}


def _json_safe(obj: Any) -> Any:
    """Best-effort conversion for bundle serialization — events may
    carry exceptions, ServerIds, numpy scalars; a bundle write must
    never fail on a field repr."""
    return repr(obj)


class FlightRecorder:
    """Bounded per-subsystem structured-event rings + bundle dumps.

    The subsystem is the event type's dotted prefix (``wal.fsync`` ->
    ring ``wal``), so one noisy plane can never evict another plane's
    history — the property that makes the recorder useful at the crash
    site (the engine's kHz dispatch events do not wash out the three
    supervisor events that explain the death)."""

    DEFAULT_RING = 4096

    def __init__(self, ring_capacity: int = DEFAULT_RING) -> None:
        self.ring_capacity = int(ring_capacity)
        self._rings: dict[str, collections.deque] = {}
        #: named zero-arg state callables merged into every bundle
        #: (Observatory snapshot, WAL watermarks, fault-plan state...)
        self._sources: dict[str, Callable[[], Any]] = {}
        #: newest-first incident log (what/where/when + bundle path)
        self.incidents: collections.deque = collections.deque(maxlen=32)
        #: master switch: False turns record() into one attr read + a
        #: bool test (the A/B knob the overhead pin flips)
        self.enabled = True
        #: where dump() writes when the trigger site has no data_dir;
        #: None -> $RA_TPU_BLACKBOX_DIR -> <tmp>/ra_tpu_blackbox
        self.dump_dir: Optional[str] = None
        self.origin = f"pid{os.getpid()}"
        self.counters = {"events": 0, "unregistered_events": 0,
                         "dumps": 0, "dump_errors": 0, "recoveries": 0}
        self._dump_lock = threading.Lock()
        self._dump_seq = 0

    # -- emit path (rides dispatch loops and WAL threads: stay cheap) --

    def record(self, etype: str, **fields: Any) -> None:
        """Append one structured event to its subsystem ring.  One dict
        lookup + one deque append; never blocks, never raises, never
        touches a device array (rule RA06/RA04-gated)."""
        if not self.enabled:
            return
        sub = etype.partition(".")[0]
        ring = self._rings.get(sub)
        if ring is None:
            ring = self._rings.setdefault(
                sub, collections.deque(maxlen=self.ring_capacity))
        if etype not in EVENT_REGISTRY:
            # the runtime mirror of lint rule RA06: a typo'd event type
            # is still recorded (evidence beats purity at a crash
            # site) but counted so tests can pin the mismatch to 0
            self.counters["unregistered_events"] += 1
        ring.append((time.time(), etype, fields))
        self.counters["events"] += 1

    # -- wiring --------------------------------------------------------

    def add_source(self, name: str, fn: Callable[[], Any]) -> None:
        """Register a state source merged into every bundle.  Sources
        are fault-isolated at dump time (a failing one contributes an
        ``error`` entry, the dump still lands)."""
        self._sources[name] = fn

    def remove_source(self, name: str, fn: Optional[Callable] = None) -> None:
        """Drop a source; with ``fn`` given, only when it is still the
        registered one (a closed engine must not unhook its
        successor's source under the shared name)."""
        if fn is None or self._sources.get(name) is fn:
            self._sources.pop(name, None)

    def clear(self, *, sources: bool = False) -> None:
        """Drop every ring and incident (test isolation).  Sources are
        KEPT by default — module-level wiring (fault-plan registries)
        registers once per process and must survive a ring wipe."""
        self._rings.clear()
        self.incidents.clear()
        if sources:
            self._sources.clear()
        for k in self.counters:
            self.counters[k] = 0

    # -- readout -------------------------------------------------------

    def events(self, subsystem: Optional[str] = None) -> list:
        """Recorded events as [(ts, etype, fields)], oldest first —
        one subsystem's ring, or every ring merged and time-sorted."""
        rings = ([self._rings.get(subsystem, ())] if subsystem
                 else list(self._rings.values()))
        out: list = []
        for ring in rings:
            got: list = []
            for _ in range(3):
                # deque iteration can race a concurrent append
                # ("deque mutated during iteration"); retry into a
                # FRESH list so a failed attempt's partial copy never
                # duplicates events — readers are rare, appends must
                # never wait on them
                try:
                    got = list(ring)
                    break
                except RuntimeError:  # pragma: no cover — append race
                    got = []
                    continue
            out.extend(got)
        out.sort(key=lambda e: e[0])
        return out

    def last_incident(self) -> Optional[dict]:
        return self.incidents[-1] if self.incidents else None

    def overview(self) -> dict:
        """Host-side health summary (what the Observatory embeds)."""
        return {"counters": dict(self.counters),
                "rings": {k: len(v) for k, v in self._rings.items()},
                "last_incident": self.last_incident()}

    # -- post-mortem bundles -------------------------------------------

    def _resolve_dir(self, data_dir: Optional[str]) -> str:
        if data_dir:
            return os.path.join(data_dir, "blackbox")
        if self.dump_dir:
            return self.dump_dir
        env = os.environ.get("RA_TPU_BLACKBOX_DIR")
        if env:
            return env
        return os.path.join(tempfile.gettempdir(), "ra_tpu_blackbox")

    def dump(self, reason: str, *, what: str = "", where: str = "",
             data_dir: Optional[str] = None,
             extra: Optional[dict] = None) -> Optional[str]:
        """Write a post-mortem bundle and log the incident.  Returns
        the bundle path, or None when the write itself failed (an
        ENOSPC'd disk must not add a crash to the crash — counted in
        ``dump_errors``).  Trigger sites pass their ``data_dir`` so
        bundles land next to the data they explain."""
        ts = time.time()
        with self._dump_lock:
            self._dump_seq += 1
            seq = self._dump_seq
        # the whole build+write is guarded: dump() is called from crash
        # handlers, so ANY escape (a ring dict resized by a concurrent
        # first-event record, a non-string dict key json refuses, a
        # full disk) must degrade to a counted dump_error — a failing
        # dump must never add a crash to the crash (doc'd contract)
        try:
            bundle = {
                "format": "ra-tpu-blackbox-1",
                "reason": reason,
                "what": what,
                "where": where,
                "ts": ts,
                "origin": self.origin,
                "pid": os.getpid(),
                "counters": dict(self.counters),
                "incidents": list(self.incidents),
                "events": {sub: self.events(sub)
                           for sub in list(self._rings)},
                "sources": {},
                "extra": extra or {},
            }
            for name, fn in list(self._sources.items()):
                try:
                    bundle["sources"][name] = fn()
                except Exception as exc:  # noqa: BLE001 — degrade
                    bundle["sources"][name] = {"error": repr(exc)[:200]}
            out_dir = self._resolve_dir(data_dir)
            path = os.path.join(
                out_dir, f"bundle-{int(ts)}-{os.getpid()}-{seq:03d}-"
                f"{reason[:40]}.json")
            os.makedirs(out_dir, exist_ok=True)
            tmp = path + ".partial"
            with open(tmp, "w") as f:
                json.dump(bundle, f, default=_json_safe,
                          separators=(",", ":"), skipkeys=True)
            os.replace(tmp, path)
        except Exception:  # noqa: BLE001 — never raise from a dump
            self.counters["dump_errors"] += 1
            logger.exception("flight recorder: bundle dump failed "
                             "(%s)", reason)
            return None
        incident = {"ts": ts, "reason": reason, "what": what,
                    "where": where, "path": path}
        self.incidents.append(incident)
        self.counters["dumps"] += 1
        self.record("bb.dump", reason=reason, what=what, where=where,
                    path=path)
        logger.warning("flight recorder: post-mortem bundle %s (%s)",
                       path, reason)
        return path

    def stamp_recovery(self, info: dict,
                       data_dir: Optional[str] = None) -> Optional[str]:
        """Write a recovery report that joins the newest bundle in the
        same blackbox dir (``joins`` names it, or None for a clean
        boot) — crash and recovery read as one incident."""
        ts = time.time()
        out_dir = self._resolve_dir(data_dir)
        joins = None
        try:
            names = sorted(n for n in os.listdir(out_dir)
                           if n.startswith("bundle-")
                           and n.endswith(".json"))
            joins = names[-1] if names else None
        except OSError:
            pass
        report = {"format": "ra-tpu-recovery-1", "ts": ts,
                  "origin": self.origin, "joins": joins, **info}
        path = os.path.join(out_dir,
                            f"recovery-{int(ts)}-{os.getpid()}.json")
        try:
            os.makedirs(out_dir, exist_ok=True)
            tmp = path + ".partial"
            with open(tmp, "w") as f:
                json.dump(report, f, default=_json_safe, skipkeys=True)
            os.replace(tmp, path)
        except Exception:  # noqa: BLE001 — recovery must not fail on this
            self.counters["dump_errors"] += 1
            logger.exception("flight recorder: recovery stamp failed")
            return None
        self.counters["recoveries"] += 1
        self.record("bb.recover", joins=joins, path=path,
                    plane=info.get("plane", "?"))
        return path


#: the process-wide recorder.  Always on (the black-box contract); the
#: rings are bounded, so "on" costs memory O(subsystems * capacity)
#: and one deque append per event.
RECORDER = FlightRecorder()


def record(etype: str, **fields: Any) -> None:
    """Emit one flight-recorder event (module-level convenience — the
    instrumented call sites all route through here; RA06 gates the
    event types statically)."""
    RECORDER.record(etype, **fields)


def stamp_recovery(info: dict, data_dir: Optional[str] = None):
    return RECORDER.stamp_recovery(info, data_dir=data_dir)


def load_bundle(path: str) -> dict:
    """Parse a post-mortem bundle (the ra_trace input contract)."""
    with open(path) as f:
        doc = json.load(f)
    if doc.get("format") != "ra-tpu-blackbox-1":
        raise ValueError(f"not a ra-tpu blackbox bundle: {path}")
    return doc
