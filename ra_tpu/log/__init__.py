from .memory import MemoryLog
