from .durable import DurableLog
from .memory import MemoryLog
from .segment import SegmentFile, SegmentWriter
from .snapshot import DEFAULT_SNAPSHOT_MODULE, SnapshotModule
from .wal import Wal
