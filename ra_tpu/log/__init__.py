from .durable import DurableLog
from .memory import MemoryLog
from .segment import SegmentFile, SegmentWriter
from .wal import Wal
