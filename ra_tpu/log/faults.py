"""Deterministic storage-plane fault injection — the disk twin of the
transport FaultPlan (transport/rpc.py:336).

Everything in ``ra_tpu.log`` does its file I/O through the :data:`IO`
shim below instead of the raw native facade.  With no plan installed
the shim is a plain passthrough (one attribute check per call).  With a
:class:`DiskFaultPlan` installed, every (path-class, op) stream owns a
private RNG seeded from the plan seed + the stream key, so one
stream's draws never perturb another's and a schedule replays
identically whatever the thread interleaving — the same determinism
contract as the wire plan.

Fault taxonomy (the storage failure modes the degradation policy in
wal.py/segment.py/durable.py must answer):

* ``fsync_eio``    — the durability syscall fails (EIO).  fsyncgate
  discipline: after a failed fsync the kernel may have dropped the
  dirty pages, so re-issuing fsync on the same fd and treating success
  as durability is a silent-loss bug.  The shim tracks failed fds and
  counts any fsync re-issued with NO intervening write to that fd as
  ``fsync_retries_after_failure`` (must stay 0).  NB the oracle is
  deliberately write-granular, not range-granular: any write clears
  the poison mark, because the one legitimate re-sync path — the
  segment-flush retry — re-issues the FULL pending batch (identical
  pwrites, pages re-dirtied).  A policy that appended fresh data to a
  poisoned fd and re-synced would evade this counter; the WAL policy
  makes that structurally impossible by retiring a poisoned file
  before any further write.
* ``enospc``       — write fails up front, nothing lands.
* ``short_write``  — a torn write: a PREFIX of the buffer really
  reaches the file, then the call errors.  Recovery must stop at the
  damage point via crc, not mis-file the tail.
* ``corrupt_read`` — read-side bit rot: one bit of the returned bytes
  is flipped.  Every read path carries a crc; the checks must catch it
  (counted as ``crc_catches`` by the catching layer).
* ``slow``         — the op sleeps ``slow_ms`` first (latency chaos).

Path classes: ``wal`` (\\*.wal), ``segment`` (\\*.segment / \\*.trunc),
``snapshot`` (\\*.rtsn / accept.partial / snapshot+checkpoints dirs),
``meta`` (meta / meta.partial), ``other``.  Ops: ``write``, ``fsync``,
``read``.

Node-wide observability rides :data:`DISK_COUNTERS`
(metrics.DISK_FAULT_FIELDS), merged into ``RaSystem.counters()`` and
the engine WAL overview.
"""
from __future__ import annotations

import errno
import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Optional

from ..blackbox import RECORDER, record
from ..metrics import DISK_FAULT_FIELDS
from ..native import IO as _NATIVE

#: node-wide disk-fault counters (GIL-atomic increments, like the
#: per-component counter dicts elsewhere)
DISK_COUNTERS: dict = {f: 0 for f in DISK_FAULT_FIELDS}


def note(field: str, n: int = 1) -> None:
    DISK_COUNTERS[field] = DISK_COUNTERS.get(field, 0) + n


def disk_fault_counters() -> dict:
    return dict(DISK_COUNTERS)


def reset_disk_fault_counters() -> None:
    for f in list(DISK_COUNTERS):
        DISK_COUNTERS[f] = 0


def classify_path(path: str) -> str:
    """Path class of a storage file (the fault-plan routing key)."""
    name = os.path.basename(path)
    if name.endswith(".wal"):
        return "wal"
    if name.endswith(".segment") or name.endswith(".trunc"):
        return "segment"
    parent = os.path.basename(os.path.dirname(path))
    if name.endswith(".rtsn") or name.endswith(".rtsn.partial") or \
            name == "accept.partial" or parent in ("snapshot",
                                                   "checkpoints"):
        return "snapshot"
    if name in ("meta", "meta.partial"):
        return "meta"
    return "other"


@dataclass(frozen=True)
class DiskFaultSpec:
    """Per-stream fault probabilities.  ``limit`` bounds the TOTAL
    faults this spec may inject on one stream (0 = unbounded) — a limit
    of 2 with ``fsync_eio=1.0`` means 'fail exactly the first two
    fsyncs', which is how tests script deterministic scenarios.
    ``path_match`` narrows a rule to paths containing the substring
    (e.g. ``shard03`` to target one WAL shard)."""

    fsync_eio: float = 0.0
    enospc: float = 0.0
    short_write: float = 0.0
    corrupt_read: float = 0.0
    slow: float = 0.0
    slow_ms: tuple = (1.0, 5.0)
    limit: int = 0
    path_match: str = ""

    @property
    def quiet(self) -> bool:
        return (self.fsync_eio == self.enospc == self.short_write ==
                self.corrupt_read == self.slow == 0)


#: which fault kinds apply to which op (spec field -> injected kind)
_OP_KINDS = {
    "fsync": (("fsync_eio", "fsync_eio"), ("slow", "slow")),
    "write": (("enospc", "enospc"), ("short_write", "short_write"),
              ("slow", "slow")),
    "read": (("corrupt_read", "corrupt_read"), ("slow", "slow")),
}


class DiskFaultPlan:
    """Seeded fault schedule consulted by the storage I/O shim.

    Rules resolve most-specific-first: the first entry of ``rules``
    whose path-class matches (``*`` = any) and whose ``path_match``
    substring appears in the path, then ``by_class[path_class]``, then
    the default.  Every (rule, path_class, op) stream owns a private
    RNG seeded from the plan seed + the key.
    """

    def __init__(self, seed: int = 0,
                 default: Optional[DiskFaultSpec] = None,
                 by_class: Optional[dict] = None,
                 rules: Optional[list] = None) -> None:
        self.seed = seed
        self.default = default or DiskFaultSpec()
        self.by_class = dict(by_class or {})
        #: [(path_class_or_star, DiskFaultSpec)] — checked in order
        self.rules = list(rules or [])
        self._rngs: dict = {}
        self._spent: dict = {}
        self._lock = threading.Lock()
        #: injected-fault counters by kind
        self.counters: dict = {}

    def _spec_for(self, path_class: str, path: str):
        for i, (cls, spec) in enumerate(self.rules):
            if cls in ("*", path_class) and spec.path_match in path:
                return ("rule", i), spec
        spec = self.by_class.get(path_class)
        if spec is not None:
            return ("class", path_class), spec
        return ("default",), self.default

    def decide(self, path_class: str, op: str, path: str = "") -> tuple:
        """-> (kind, param): kind in {"ok", "fsync_eio", "enospc",
        "short_write", "corrupt_read", "slow"}; param is the sleep
        seconds for "slow", else 0."""
        rid, spec = self._spec_for(path_class, path)
        if spec.quiet:
            return ("ok", 0)
        key = (rid, path_class, op)
        with self._lock:
            rng = self._rngs.get(key)
            if rng is None:
                rng = self._rngs[key] = random.Random(
                    f"{self.seed}:{rid}:{path_class}:{op}")
            if spec.limit and self._spent.get(key, 0) >= spec.limit:
                return ("ok", 0)
            roll = rng.random()
            edge = 0.0
            for field, kind in _OP_KINDS.get(op, ()):
                prob = getattr(spec, field)
                edge += prob
                if roll >= edge:
                    continue
                self._spent[key] = self._spent.get(key, 0) + 1
                self.counters[kind] = self.counters.get(kind, 0) + 1
                note("faults_injected")
                # every injected storage fault names itself in the
                # flight recorder: a post-mortem bundle can point at
                # the exact faulted op, not just a counter
                record("disk.fault", kind=kind, path_class=path_class,
                       op=op, path=os.path.basename(path) if path
                       else "")
                if kind == "slow":
                    lo, hi = spec.slow_ms
                    return ("slow", rng.uniform(lo, hi) / 1000.0)
                if kind == "corrupt_read":
                    # deterministic damage: bit position drawn from the
                    # stream RNG, applied by the shim to the read bytes
                    return ("corrupt_read", rng.random())
                return (kind, 0)
        return ("ok", 0)

    def overview(self) -> dict:
        """Plan state for post-mortem bundles: seed, targeting rules
        and per-kind injection counts — a bundle must NAME the chaos
        that was active when the system died."""
        def _spec(s: DiskFaultSpec) -> dict:
            d = {f: getattr(s, f) for f in
                 ("fsync_eio", "enospc", "short_write", "corrupt_read",
                  "slow") if getattr(s, f)}
            if s.limit:
                d["limit"] = s.limit
            if s.path_match:
                d["path_match"] = s.path_match
            return d

        return {"seed": self.seed,
                "default": _spec(self.default),
                "by_class": {c: _spec(s)
                             for c, s in self.by_class.items()},
                "rules": [[c, _spec(s)] for c, s in self.rules],
                "injected": dict(self.counters)}


class FaultyIO:
    """Thin shim over the native I/O facade, consulted by everything in
    ``ra_tpu.log``.  Tracks fd -> (path_class, path) so positioned I/O
    on an fd resolves its fault stream, and enforces the fsyncgate
    bookkeeping (failed-fsync fds are remembered until their data is
    rewritten)."""

    def __init__(self, base) -> None:
        self._base = base
        self.plan: Optional[DiskFaultPlan] = None
        self._fd_info: dict = {}
        self._failed_sync_fds: set = set()
        self._lock = threading.Lock()

    # -- plan lifecycle -----------------------------------------------------

    def install(self, plan: Optional[DiskFaultPlan]) -> None:
        self.plan = plan

    def uninstall(self) -> None:
        self.plan = None
        with self._lock:
            self._failed_sync_fds.clear()

    # -- passthroughs -------------------------------------------------------

    @property
    def native(self) -> bool:
        return self._base.native

    def stats(self) -> dict:
        return self._base.stats()

    def crc32(self, data: bytes, seed: int = 0) -> int:
        return self._base.crc32(data, seed)

    # -- opens (register the fd's fault stream) -----------------------------

    def wal_open(self, path: str, truncate: bool = False,
                 o_sync: bool = False) -> int:
        fd = self._base.wal_open(path, truncate=truncate, o_sync=o_sync)
        with self._lock:
            self._fd_info[fd] = (classify_path(path), path)
            self._failed_sync_fds.discard(fd)
        return fd

    def random_open(self, path: str, truncate: bool = False) -> int:
        fd = self._base.random_open(path, truncate=truncate)
        with self._lock:
            self._fd_info[fd] = (classify_path(path), path)
            self._failed_sync_fds.discard(fd)
        return fd

    def close(self, fd: int) -> None:
        with self._lock:
            self._fd_info.pop(fd, None)
            self._failed_sync_fds.discard(fd)
        self._base.close(fd)

    def _info(self, fd: int) -> tuple:
        return self._fd_info.get(fd, ("other", ""))

    def _decide(self, fd: int, op: str,
                path_class: Optional[str] = None) -> tuple:
        plan = self.plan
        if plan is None:
            return ("ok", 0)
        cls, path = self._info(fd)
        if path_class is not None:
            cls = path_class
        kind, param = plan.decide(cls, op, path)
        if kind == "slow":
            time.sleep(param)
            return ("ok", 0)
        return (kind, param)

    # -- faultable ops ------------------------------------------------------

    def write_batch(self, fd: int, buf: bytes, sync_mode: int = 1) -> int:
        kind, _ = self._decide(fd, "write")
        if kind == "enospc":
            raise OSError(errno.ENOSPC, "injected: no space left on "
                          "device (DiskFaultPlan)")
        if kind == "short_write":
            # a torn write: half the buffer really lands, then the call
            # errors — the crc discipline must stop recovery here
            torn = buf[:max(1, len(buf) // 2)]
            self._base.write_batch(fd, torn, 0)
            raise OSError(errno.EIO, "injected: short/torn write "
                          "(DiskFaultPlan)")
        n = self._base.write_batch(fd, buf, sync_mode)
        with self._lock:
            self._failed_sync_fds.discard(fd)  # data rewritten/extended
        return n

    def pwrite(self, fd: int, buf: bytes, off: int) -> int:
        kind, _ = self._decide(fd, "write")
        if kind == "enospc":
            raise OSError(errno.ENOSPC, "injected: no space left on "
                          "device (DiskFaultPlan)")
        if kind == "short_write":
            torn = buf[:max(1, len(buf) // 2)]
            self._base.pwrite(fd, torn, off)
            raise OSError(errno.EIO, "injected: short/torn pwrite "
                          "(DiskFaultPlan)")
        n = self._base.pwrite(fd, buf, off)
        with self._lock:
            self._failed_sync_fds.discard(fd)
        return n

    def pread(self, fd: int, length: int, off: int) -> bytes:
        data = self._base.pread(fd, length, off)
        kind, param = self._decide(fd, "read")
        if kind == "corrupt_read" and data:
            data = self._flip_bit(data, param)
        return data

    def sync(self, fd: int, mode: int = 1,
             path_class: Optional[str] = None) -> None:
        if mode == 0:
            return
        # fsyncgate bookkeeping applies only to fds OPENED through the
        # shim: an unregistered fd (the path_class-override one-shot
        # handles of store_meta/complete_accept) is closed by plain
        # f.close(), so its number recycles and a stale entry in the
        # failed set would count false fsync_retries_after_failure hits
        # against whatever unrelated file lands on that number next.
        # Those call sites discard the whole file on failure anyway —
        # there is no fd to wrongly re-sync.
        with self._lock:
            tracked = fd in self._fd_info
            if tracked and fd in self._failed_sync_fds:
                # fsyncgate: an fsync re-issued on a failed fd without
                # an intervening rewrite can report success over dropped
                # pages — the degradation policy must never do this
                note("fsync_retries_after_failure")
        kind, _ = self._decide(fd, "fsync", path_class=path_class)
        if kind == "fsync_eio":
            if tracked:
                with self._lock:
                    self._failed_sync_fds.add(fd)
            raise OSError(errno.EIO, "injected: fsync failed "
                          "(DiskFaultPlan)")
        self._base.sync(fd, mode)

    def fsync_failed(self, fd: int) -> bool:
        """True when a durability syscall on this fd has failed and its
        data has not been rewritten since (the fd is poisoned)."""
        with self._lock:
            return fd in self._failed_sync_fds

    def read_file(self, path: str) -> bytes:
        """Whole-file read with read-side fault injection (the recovery
        scan path of WAL files and snapshot containers, which bypasses
        positioned fd I/O)."""
        with open(path, "rb") as f:
            data = f.read()
        plan = self.plan
        if plan is None or not data:
            return data
        kind, param = plan.decide(classify_path(path), "read", path)
        if kind == "slow":
            time.sleep(param)
        elif kind == "corrupt_read":
            data = self._flip_bit(data, param)
        return data

    @staticmethod
    def _flip_bit(data: bytes, roll: float) -> bytes:
        pos = min(len(data) - 1, int(roll * len(data)))
        b = bytearray(data)
        b[pos] ^= 1 << (pos % 8)
        return bytes(b)


#: the storage-plane I/O facade — ra_tpu.log modules import THIS
IO = FaultyIO(_NATIVE)

#: post-mortem bundles embed the ACTIVE DiskFaultPlan (None = no chaos
#: installed) plus the node-wide fault counters
RECORDER.add_source(
    "disk_fault_plan",
    lambda: {"plan": (IO.plan.overview() if IO.plan is not None
                      else None),
             "counters": disk_fault_counters()})


def install_plan(plan: Optional[DiskFaultPlan]) -> None:
    """Install a node-wide disk fault plan (None clears it)."""
    if plan is None:
        IO.uninstall()
    else:
        IO.install(plan)


def clear_plan() -> None:
    IO.uninstall()


def current_plan() -> Optional[DiskFaultPlan]:
    return IO.plan
