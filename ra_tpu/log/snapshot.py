"""Pluggable snapshot behaviour — the ra_snapshot module contract.

Mirrors /root/reference/src/ra_snapshot.erl:98-168: the snapshot
*container* (file naming, magic, crc, meta framing, pending-write and
chunked-accept state) is owned by the log layer, while the **module**
controls how machine state becomes the container's data section and
back, plus how that byte stream is cut into install_snapshot chunks.
Machines select a module by overriding ``Machine.snapshot_module()``
(/root/reference/src/ra_machine.erl:435-437); the default is the
pickle module — the ``term_to_binary`` role of ra_log_snapshot.erl.

Module contract (all callbacks pure, stateless):

* ``encode(machine_state) -> bytes`` — the ``prepare``+``write`` role
  (ra_snapshot.erl:120-128)
* ``decode(data) -> machine_state`` — the ``recover`` role (:150-156)
* ``chunks(data, size)`` — the ``begin_read``/``read_chunk`` role
  (:129-143): yield the data as transfer chunks.  Default: plain byte
  slices; override for formats with natural chunk boundaries.
* ``validate(data) -> bool`` — extra format-level validation on top of
  the container crc (:157-160).  Fault-model note (INTERNALS §6.3):
  the container layer already catches read-side bit corruption by crc
  (with one fresh-read retry) and torn writes by the pending-dir
  rename discipline, so ``validate`` only needs to reject
  *format*-level mismatches (e.g. a module change without migration) —
  it must NOT silently accept-and-reinterpret foreign bytes, which
  recover_snapshot_state treats as a loud failure rather than a
  fallback.

The follower's accept side (begin_accept/accept_chunk/complete_accept,
:144-149) is chunk-format-agnostic by construction: chunks are
re-concatenated before ``decode`` runs, so a custom module only needs
encode/decode for full install+recovery round-trips.
"""
from __future__ import annotations

import pickle
from typing import Any, Iterator


class SnapshotModule:
    """Default module: pickle (ra_log_snapshot's term_to_binary role)."""

    #: short format tag recorded for observability (context/0 role)
    name = "pickle"

    def encode(self, machine_state: Any) -> bytes:
        return pickle.dumps(machine_state,
                            protocol=pickle.HIGHEST_PROTOCOL)

    def decode(self, data: bytes) -> Any:
        return pickle.loads(data)

    def chunks(self, data: bytes, size: int) -> Iterator[bytes]:
        if not data:
            yield b""
            return
        for i in range(0, len(data), size):
            yield data[i:i + size]

    def validate(self, data: bytes) -> bool:
        return True


DEFAULT_SNAPSHOT_MODULE = SnapshotModule()
