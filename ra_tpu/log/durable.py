"""DurableLog — the per-server log facade over WAL + segments + snapshots.

Same contract as ra_tpu.log.memory.MemoryLog (the interface the pure core
consumes), with real durability.  Mirrors ra_log.erl's division:

* recent entries live in the in-process memtable and are readable
  immediately; durability is observed through written events delivered by
  the WAL after batch fsync (:474-529) — take_events() surfaces them to
  the shell exactly like the memory log
* the leader's own confirm participates in commit quorum; gap/resend and
  stale-term confirms are handled in handle_written (:521-529, :641-644)
* on WAL rollover the segment writer drains the memtable to this server's
  segment files (flush_mem_to_segments) and prunes it (:534-574)
* snapshots truncate segments and the memtable (:575-640); checkpoints
  don't truncate; promote_checkpoint renames one into the snapshot slot
  (ra_snapshot.erl:399-448); chunked accept for streamed installs
* recovery: meta file + latest valid snapshot + segment ranges + WAL
  recovered tables (:170-277 and §3.4 of SURVEY.md)

Layout under <data_dir>/<uid>/:
  meta                 pickled dict (current_term, voted_for, last_applied)
  NNNNNNNN.segment     segment files
  snapshot/snap_<idx>_<term>.rtsn
  checkpoints/cp_<idx>_<term>.rtsn
"""
from __future__ import annotations

import os
import pickle
import struct
import threading
import time
import zlib
from typing import Any, Callable, Iterable, Optional

from ..core.types import (Entry, IdxTerm, SnapshotMeta, WalUpEvent,
                          WrittenEvent)
from ..metrics import LOG_FIELDS
from ..utils.flru import Flru
from .faults import IO, note as _fault_note
from .segment import DEFAULT_MAX_COUNT, SegmentFile
from .snapshot import DEFAULT_SNAPSHOT_MODULE

#: open segment fds per server (ra_flru's open_segments cap,
#: ra_log_reader.erl:45-49)
MAX_OPEN_SEGMENTS = 5

SNAP_MAGIC = b"RTSN"
_SNAP_HDR = struct.Struct("<4sII")  # magic, version, crc(meta+state)

#: sentinel for "not answerable from memtable/snapshot alone" — the
#: under-_lock half of a term lookup returns it instead of falling
#: through to a segment read (_io_lock), see _mem_term_locked (RA11)
_MISS = object()

MAX_CHECKPOINTS = 10  # ra.hrl:234

#: the durable command image is owned by ra_tpu.codec since ISSUE 18 —
#: one schema'd layout from socket to segment, with the pre-codec 0x01
#: fast-tuple frame and raw-pickle images kept as decode-only legacy
#: branches so r06-era WAL/segment dirs still recover.  Re-exported here
#: because every log-plane consumer (and lint rule RA10's encoder-name
#: resolution) imports the pair from this module.
from ..codec import decode_command, encode_command  # noqa: E402  (re-export)


def _write_snapshot_file(path: str, meta: SnapshotMeta, data: bytes) -> None:
    """Pending-dir discipline: the container is written+fsynced to a
    ``.partial`` sibling and only then renamed into the slot, so a torn
    write can NEVER shadow a good snapshot — on any I/O error the
    OSError propagates before the rename and the old container stays
    authoritative.  Writes ride the storage I/O shim (fault-injectable,
    log/faults.py)."""
    meta_b = pickle.dumps(meta)
    body = struct.pack("<I", len(meta_b)) + meta_b + data
    crc = IO.crc32(body)
    tmp = path + ".partial"
    fd = IO.random_open(tmp, truncate=True)
    try:
        IO.pwrite(fd, _SNAP_HDR.pack(SNAP_MAGIC, 1, crc) + body, 0)
        IO.sync(fd, 2)
    finally:
        IO.close(fd)
    os.replace(tmp, path)


def _drop_partial(path: str) -> None:
    """Remove the ``.partial`` leftover of a failed container write."""
    try:
        os.unlink(path + ".partial")
    except OSError:
        # safe to swallow: a stranded .partial can never shadow a real
        # container (recovery only reads fully-renamed files) — it only
        # leaks bytes until the next write truncates it
        _fault_note("swallowed_oserrors")


def _parse_snapshot_bytes(raw: bytes) -> Optional[tuple]:
    try:
        magic, _version, crc = _SNAP_HDR.unpack_from(raw, 0)
        body = raw[_SNAP_HDR.size:]
        if magic != SNAP_MAGIC or IO.crc32(body) != crc:
            return None
        (mlen,) = struct.unpack_from("<I", body, 0)
        meta = pickle.loads(body[4:4 + mlen])
        return meta, body[4 + mlen:]
    except Exception:
        return None


def _read_snapshot_file(path: str) -> Optional[tuple]:
    """Returns (meta, data) or None when invalid (validate,
    ra_log_snapshot.erl:112+).  A crc failure is retried ONCE with a
    fresh read: transient read-side corruption must not discard a good
    container (the fallback would silently rewind machine state to an
    older image)."""
    try:
        got = _parse_snapshot_bytes(IO.read_file(path))
        if got is None:
            got = _parse_snapshot_bytes(IO.read_file(path))
            if got is not None:
                # the fresh read validated: transient read-side
                # corruption caught by the container crc — a container
                # that fails BOTH reads is genuinely invalid (torn
                # write) and is not fault telemetry
                _fault_note("crc_catches")
        return got
    except Exception:
        return None


class LogReader:
    """External-reader handle over a DurableLog's segment-flushed entries
    (the registered-reader role, ra_log.erl:983-1008).  Reads resolve
    per-call under the log's io lock; while the reader is open, snapshot
    truncation pins (rather than deletes) covered segment files, so a
    slow reader never loses entries it could already see.  Entries still
    in the memtable (not yet segment-flushed) are NOT visible — the
    reference's external readers consume flushed segrefs only."""

    def __init__(self, log: "DurableLog", name: str) -> None:
        self._log = log
        self.name = name
        self._closed = False

    def fetch(self, idx: int) -> Optional[Entry]:
        got = self._log._reader_read(idx)
        if got is None:
            return None
        term, payload = got
        return Entry(idx, term, decode_command(payload))

    def sparse_read(self, indexes: Iterable[int]) -> list:
        out = []
        for i in indexes:
            e = self.fetch(i)
            if e is not None:
                out.append(e)
        return out

    def fold(self, from_idx: int, to_idx: int, fn: Callable,
             acc: Any) -> Any:
        for i in range(from_idx, to_idx + 1):
            e = self.fetch(i)
            if e is not None:
                acc = fn(e, acc)
        return acc

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._log.close_reader(self.name)

    def __enter__(self) -> "LogReader":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


class DurableLog:
    #: pluggable state serializer (Machine.snapshot_module override,
    #: ra_machine.erl:435-437); container format is module-agnostic
    #: True when term/voted_for/entries survive a process restart —
    #: gates supervised auto-restart (amnesia double-vote hazard)
    durable = True

    snapshot_module = DEFAULT_SNAPSHOT_MODULE

    def __init__(self, uid: str, data_dir: str, wal, *,
                 segment_max_count: int = DEFAULT_MAX_COUNT) -> None:
        self.uid = uid
        self.dir = os.path.join(data_dir, uid)
        os.makedirs(self.dir, exist_ok=True)
        os.makedirs(os.path.join(self.dir, "snapshot"), exist_ok=True)
        os.makedirs(os.path.join(self.dir, "checkpoints"), exist_ok=True)
        self.wal = wal
        self.segment_max_count = segment_max_count
        self._lock = threading.RLock()
        # serializes segment-file I/O (flush vs snapshot truncation vs
        # reads); ordering discipline: _io_lock before _lock, never inverse
        self._io_lock = threading.Lock()
        self._events: list = []            # pending events for the shell
        # idx -> Entry: reads hand back the stored object, so the apply
        # fold and AER build pay ZERO per-entry construction (the
        # Entry-per-read rebuild was ~5 namedtuple ctors per command on
        # the classic plane, ISSUE 18)
        self._memtable: dict[int, Entry] = {}
        self._mem_bytes: dict[int, bytes] = {}  # idx -> payload (for flush)
        # creation order, newest LAST — load-bearing: _segment_read scans
        # reversed so a newer segment's entries supersede older ones where
        # they overlap, and _current_segment appends to [-1]
        self._segments: list[SegmentFile] = []
        # caps open descriptors: indexes stay in memory, evicted segments
        # reopen transparently on the next read (guarded by _io_lock)
        self._open_segments = Flru(
            MAX_OPEN_SEGMENTS, on_evict=lambda _path, seg: seg.close_fd())
        self._seg_seq = 0
        self._last_index = 0
        self._last_term = 0
        self._last_written = IdxTerm(0, 0)
        self._first_index = 1
        self._meta: dict = {"current_term": 0, "voted_for": None,
                            "last_applied": 0}
        self._snapshot: Optional[tuple] = None  # (meta, path)
        self._checkpoints: list[tuple] = []     # [(meta, path)] sorted asc
        self._truncate_next = False
        #: registered external readers (ra_log.erl:983-1008) and segments
        #: kept alive for them past a snapshot truncation.  name -> count:
        #: two consumers may register under the same name; the pins hold
        #: until the LAST registration closes
        self._readers: dict = {}
        self._pinned_segments: list = []
        #: log-subsystem counters (RA_LOG_COUNTER_FIELDS, ra.hrl:236-268);
        #: GIL-atomic dict increments, merged into key_metrics
        self.counters: dict[str, int] = {f: 0 for f in LOG_FIELDS}
        #: in-flight chunked snapshot accept stream (begin_accept)
        self._accept: Optional[dict] = None
        #: WAL incarnation this log has resent its unconfirmed tail to
        #: (the new-wal-pid check of ra_log.erl:778-793, kept per-put so
        #: no append can race the supervisor's resend hook)
        self._wal_generation = wal.generation
        self._recover_state()
        wal.register(uid, self._wal_notify)

    # ------------------------------------------------------------------
    # recovery
    # ------------------------------------------------------------------

    def _recover_state(self) -> None:
        meta_path = os.path.join(self.dir, "meta")
        if os.path.exists(meta_path):
            try:
                with open(meta_path, "rb") as f:
                    self._meta.update(pickle.load(f))
            except Exception:
                pass
        # newest valid snapshot wins; fall back to older ones
        # (ra_snapshot.erl:183-222)
        snapdir = os.path.join(self.dir, "snapshot")
        # a stale accept stream from an interrupted install is garbage:
        # the leader restarts the transfer from chunk 1
        stale_accept = os.path.join(snapdir, "accept.partial")
        if os.path.exists(stale_accept):
            os.unlink(stale_accept)
        cands = sorted(os.listdir(snapdir), reverse=True)
        for fname in cands:
            got = _read_snapshot_file(os.path.join(snapdir, fname))
            if got is not None:
                self._snapshot = (got[0], os.path.join(snapdir, fname))
                break
        cpdir = os.path.join(self.dir, "checkpoints")
        for fname in sorted(os.listdir(cpdir)):
            got = _read_snapshot_file(os.path.join(cpdir, fname))
            if got is not None:
                self._checkpoints.append((got[0],
                                          os.path.join(cpdir, fname)))
        snap_idx = self._snapshot[0].index if self._snapshot else 0
        # segments in creation order, newest last: a newer segment's
        # entries supersede older ones wherever they overlap
        # (ra_log_reader:update_segments compaction, :93-108), and the
        # NEWEST segment defines the durable tail — an older segment
        # holding higher indexes is a stale tail from before an overwrite
        found = []
        for fname in sorted(os.listdir(self.dir)):
            if fname.endswith(".trunc"):
                # leftover of a truncate_from interrupted between writing
                # the fresh copy and the atomic rename — always safe to
                # delete (the original segment was only ever replaced
                # atomically, so it is still intact)
                try:
                    os.unlink(os.path.join(self.dir, fname))
                except FileNotFoundError:
                    pass
                continue
            if not fname.endswith(".segment"):
                continue
            seq = int(fname.split(".")[0])
            self._seg_seq = max(self._seg_seq, seq)
            try:
                seg = SegmentFile(os.path.join(self.dir, fname))
            except (ValueError, struct.error):
                continue  # corrupt header/index: skip the file
            # NB: OSError deliberately propagates — EMFILE/EIO here is an
            # environment fault; swallowing it would drop committed
            # entries and report a short log as healthy
            r = seg.range()
            if r is None or r[1] <= snap_idx:
                # empty, or wholly covered by the snapshot: a pinned
                # segment left behind by a shutdown with an open reader —
                # dead weight below first_index, reclaim it now
                seg.close()
                os.unlink(os.path.join(self.dir, fname))
                continue
            found.append((seq, seg))
            # enforce the fd cap DURING the scan: a long log would
            # otherwise hold every fd open until the post-scan eviction
            self._open_segments.touch(seg.path, seg)
        found.sort(key=lambda p: p[0])
        self._segments = [seg for _seq, seg in found]
        last, last_term = 0, 0
        if self._segments:
            lo, hi = self._segments[-1].range()
            last = hi
            last_term = self._segments[-1].read(hi)[0]
        # WAL recovered entries (newer than segments).  If a WAL entry
        # CONFLICTS with segment content at the same index (different
        # term), that write overwrote the log from there: segment entries
        # above the WAL table's own tail are stale and must not define
        # last_index (the ra_log init equivalent: the memtable range wins
        # over overlapping segment refs, ra_log.erl:199-277).  The term
        # comparison matters: a retained stale WAL file (kept because
        # another uid on the node was unresolved at flush time) overlaps
        # already-flushed segments with *agreeing* terms, and rewinding on
        # mere overlap would lose acknowledged entries above it.  Checked
        # against the RAW table — the snapshot floor is applied after,
        # else a snapshot covering the overwrite record hides the
        # truncation and resurrects the stale segment tail.
        wal_items = sorted(self.wal.recovered_table(self.uid).items())
        for idx, (term, _payload) in wal_items:
            if idx > last:
                break
            got = self._segment_read(idx)
            if got is not None and got[0] != term:
                last = wal_items[-1][0]
                last_term = wal_items[-1][1][0]
                break
        for idx, (term, payload) in wal_items:
            if idx <= snap_idx:
                continue
            cmd = decode_command(payload)
            self._memtable[idx] = Entry(idx, term, cmd)
            self._mem_bytes[idx] = payload
            if idx >= last:
                last, last_term = idx, term
        # contiguity clamp: a Raft log can never have holes.  A crash
        # that lost an unconfirmed torn batch while later entries
        # reached a newer WAL file would otherwise recover a
        # committed-LOOKING tail over a missing middle — a log whose
        # last_index could win elections it must lose.  Those covered
        # indexes were never acknowledged by this node (confirmation is
        # contiguous by construction), so dropping everything above the
        # first gap presents an honest, strictly-shorter log that the
        # current leader simply back-fills.
        covered = set(self._memtable)
        for seg in self._segments:
            covered.update(seg.index)
        probe = snap_idx
        while probe + 1 in covered:
            probe += 1
        if probe < last:
            import logging
            logging.getLogger("ra_tpu").warning(
                "%s: recovery found a log hole above %d (tail was %d); "
                "truncating to the contiguous prefix", self.uid, probe,
                last)
            for k in [k for k in self._memtable if k > probe]:
                self._memtable.pop(k, None)
                self._mem_bytes.pop(k, None)
            last = probe
            if probe == snap_idx and self._snapshot is not None:
                last_term = self._snapshot[0].term
            elif probe in self._memtable:
                last_term = self._memtable[probe].term
            else:
                got = self._segment_read(probe) if probe else None
                last_term = got[0] if got else 0
        if snap_idx > last:
            last, last_term = snap_idx, self._snapshot[0].term
        self._last_index, self._last_term = last, last_term
        self._last_written = IdxTerm(last, last_term)
        self._first_index = snap_idx + 1

    # ------------------------------------------------------------------
    # WAL callback (runs on the WAL thread)
    # ------------------------------------------------------------------

    def _wal_notify(self, uid: str, lo: Optional[int], hi: int,
                    term: int) -> None:
        rewind_term = 0
        if lo is None and term == -2:
            # pre-read OUTSIDE the log lock (rule RA11, the
            # _put/_put_batch overwrite-rewind idiom): fetch_term can
            # fall through to a segment read (_io_lock), and
            # _io_lock-inside-_lock inverts the documented io-then-log
            # order against flush_mem_to_segments — the ABBA class the
            # PR 13 review fixed on the append path survived here until
            # the RA11 analyzer flagged it.  Safe unlocked: the guard
            # below re-checks last_written under _lock before applying,
            # and an overwrite racing this read either starts <= hi
            # (rewinding last_written below hi, so the guard fails) or
            # starts above hi (leaving the term at hi untouched).  A
            # concurrent snapshot INSTALL is the remaining race (it
            # prunes <= meta.index and would leave this pre-read
            # stale), so the rewind branch re-resolves via
            # _mem_term_locked and only falls back to this value for a
            # segment-resident hi — segment terms are immutable.
            rewind_term = self.fetch_term(hi) or 0
        with self._lock:
            if lo is None:
                # resend_from: re-submit memtable entries above hi
                # (ra_log.erl:1125+).  Floor-clamped to last_written:
                # entries at/below it are durable in an EARLIER file or
                # segment and must not be re-written — a duplicate of a
                # durable entry in a LATER wal file trips the recovery
                # overwrite-dedup ("a lower index invalidates higher
                # ones") and, if that later file tears, wipes durable
                # entries from the recovered table (found by the ISSUE 4
                # poison/rollover chaos).
                if term == -2 and self._last_written.index > hi:
                    # unsynced-confirm rewind (the sync_after_notify
                    # poison path): confirms above ``hi`` rode a
                    # durability syscall that then FAILED, so they are
                    # not durable anywhere but the poisoned file — pull
                    # last_written back so the floor clamp below
                    # re-writes that suffix into the fresh file instead
                    # of trusting the poisoned one (the entries are
                    # still memtable-resident: pruning only happens at
                    # segment flush, which is gated on last_written)
                    got = self._mem_term_locked(hi)
                    if got is None and self._snapshot is not None and \
                            self._snapshot[0].index >= hi:
                        # a snapshot install landed between the
                        # pre-read and this lock and pruned <= hi.
                        # Entries up to the snapshot are durable via
                        # the snapshot — but the poisoned confirms may
                        # cover memtable entries ABOVE it, so clamp
                        # last_written to the snapshot (never below:
                        # that would stamp a stale term under durable
                        # state) and let the floor clamp below resend
                        # exactly the (snapshot, last_index] suffix
                        snap = self._snapshot[0]
                        if self._last_written.index > snap.index:
                            self._last_written = IdxTerm(snap.index,
                                                         snap.term)
                    else:
                        if got is not None and got is not _MISS:
                            rewind_term = got  # fresher than pre-read
                        self._last_written = IdxTerm(hi, rewind_term)
                start = max(hi, self._last_written.index) + 1
                for idx in range(start, self._last_index + 1):
                    ent = self._memtable.get(idx)
                    raw = self._mem_bytes.get(idx)
                    if ent is not None and raw is not None:
                        self.counters["write_resends"] += 1
                        self.wal.write(self.uid, idx, ent.term, raw)
                return
            self._events.append(WrittenEvent(lo, hi, term))

    def wal_restarted(self) -> None:
        """Supervisor hook after Wal.restart(): resend every memtable
        entry above last_written to the new WAL incarnation, then surface
        a WalUpEvent so a core parked in await_condition(wal_down)
        resumes.  This is the writer half of the reference's new-wal-pid
        resend (ra_log.erl:778-793): everything confirmed durable stays
        put; everything submitted-but-unconfirmed goes again.

        The whole collect+resend runs under the log lock — _put submits
        under the same lock, so no live append can reach the new queue
        ahead of these resends and advance last_written over a hole."""
        with self._lock:
            if not self._resend_unconfirmed_locked():
                return  # died again mid-resend; the supervisor retries
            self._events.append(WalUpEvent(self.wal.generation))

    def _resend_unconfirmed_locked(self) -> bool:
        """Resend every memtable entry above last_written to the current
        WAL incarnation and record its generation as synced-with.  MUST
        run under self._lock.  Returns False when the WAL died again
        mid-resend (the generation stays unsynced, so the next caller
        retries)."""
        from .wal import WalDown
        self._wal_generation = self.wal.generation
        lw = self._last_written.index
        items = [(i, self._memtable[i].term, self._mem_bytes[i])
                 for i in sorted(self._mem_bytes)
                 if lw < i <= self._last_index]
        try:
            for idx, term, raw in items:
                self.counters["write_resends"] += 1
                self.wal.write(self.uid, idx, term, raw)  # ra10-ok: crash-recovery resend, not the steady-state path
        except WalDown:
            self._wal_generation = -1  # resend incomplete: retry later
            return False
        return True

    # ------------------------------------------------------------------
    # log contract (same as MemoryLog)
    # ------------------------------------------------------------------

    def wal_is_up(self) -> bool:
        """Health probe for the core's wal_down await_condition: True when
        the fan-in batch thread is accepting writes."""
        return self.wal.alive

    def log_metrics(self) -> dict:
        """Counter snapshot for key_metrics (ra.erl:1229-1257);
        open_segments is sampled live (a gauge, ra.hrl:258)."""
        out = dict(self.counters)
        out["open_segments"] = len(self._open_segments)
        return out

    def last_index_term(self) -> IdxTerm:
        return IdxTerm(self._last_index, self._last_term)

    def last_written(self) -> IdxTerm:
        return self._last_written

    def first_index(self) -> int:
        return self._first_index

    def next_index(self) -> int:
        return self._last_index + 1

    def append(self, entry: Entry) -> None:
        if entry.index != self._last_index + 1:
            from .memory import IntegrityError
            raise IntegrityError(
                f"append gap: {entry.index} != {self._last_index + 1}")
        self._put(entry)

    def append_batch(self, entries: list,
                     payloads: Optional[list] = None) -> None:
        """Leader-path batch append (ISSUE 13): strictly-appending
        contiguous entries, one lock acquisition and ONE WAL fan-in
        submit for the whole run — the per-entry ``append`` path costs
        a lock cycle plus a WAL queue hand-off per entry, which at
        group-commit rates dominates the event-loop thread."""
        if not entries:
            return
        if entries[0].index != self._last_index + 1:
            from .memory import IntegrityError
            raise IntegrityError(
                f"append gap: {entries[0].index} != "
                f"{self._last_index + 1}")
        self._put_batch(entries, payloads)

    def write(self, entries: list,
              payloads: Optional[list] = None) -> None:
        """Follower-path batch write (may overwrite).  ``payloads`` —
        encoded durable images parallel to ``entries`` shipped inside
        the AppendEntries frame (the leader already paid the encode for
        its own WAL) — lets the whole batch reach the WAL without one
        pickle per entry (rule RA10)."""
        if not entries:
            return
        first = entries[0].index
        if first > self._last_index + 1:
            from .memory import IntegrityError
            raise IntegrityError(
                f"write gap: {first} > {self._last_index + 1}")
        self._put_batch(entries, payloads)

    def _put_batch(self, entries: list,
                   payloads: Optional[list] = None) -> None:
        """Shared batch insert: encode what wasn't shipped, handle the
        overwrite rewind ONCE for the run, bulk-load the memtable, and
        hand the WAL one contiguous fan-in submit."""
        if payloads is None or len(payloads) != len(entries):
            # local/fallback encode — the leader's own append, or a
            # catch-up resend whose source bytes were segment-flushed.
            # This is the classic plane's encode phase stamp (ISSUE 18):
            # wire-shipped batches skip this branch entirely, so a
            # falling encode_share_pct is the encode-once proof.
            ph = self.wal.phases
            t0 = time.monotonic() if ph is not None else 0.0
            payloads = [encode_command(e.command)  # ra10-ok: fallback when no shipped payloads ride the frame
                        for e in entries]
            if ph is not None:
                ph.note("encode", time.monotonic() - t0)
        self.counters["write_ops"] += len(entries)
        first = entries[0].index
        last_e = entries[-1]
        # resolve the overwrite-rewind predecessor term BEFORE taking
        # the log lock: fetch_term can fall through to a segment read
        # (_io_lock), and _io_lock-inside-_lock inverts the documented
        # lock order against flush_mem_to_segments (ABBA).  Safe to
        # pre-read: entry terms are immutable and only this (event
        # loop) thread writes/truncates the log.
        rewind_term = 0
        if first <= self._last_index and first > 1:
            rewind_term = self.fetch_term(first - 1) or 0
        with self._lock:
            if first <= self._last_index:
                # overwrite: invalidate the stale tail above the batch
                # head; rewind last_written so AER replies stay truthful
                # (the same discipline as _put, once per run)
                for k in range(last_e.index + 1, self._last_index + 1):
                    self._memtable.pop(k, None)
                    self._mem_bytes.pop(k, None)
                if self._last_written.index >= first:
                    self._last_written = IdxTerm(first - 1, rewind_term)
            memtable = self._memtable
            mem_bytes = self._mem_bytes
            items = []
            truncate = self._truncate_next
            self._truncate_next = False
            for e, payload in zip(entries, payloads):
                memtable[e.index] = e
                mem_bytes[e.index] = payload
                items.append((e.index, e.term, payload, truncate))
                truncate = False
            self._last_index = last_e.index
            self._last_term = last_e.term
            # submit under the log lock (queue.put only — no blocking);
            # same resend-before-submit generation discipline as _put
            if getattr(self, "_wal_generation", None) != \
                    self.wal.generation:
                self._resend_unconfirmed_locked()
            self.wal.write_many(self.uid, items)

    def _put(self, entry: Entry) -> None:
        # live reply handles are process-local: stripped from the durable
        # image (the memtable keeps the full command for leader replies)
        payload = encode_command(entry.command)
        self.counters["write_ops"] += 1
        # pre-read like _put_batch: a fetch_term miss under _lock would
        # take _io_lock and invert the lock order (ABBA vs segment flush)
        rewind_term = 0
        if entry.index <= self._last_index and entry.index > 1:
            rewind_term = self.fetch_term(entry.index - 1) or 0
        with self._lock:
            if entry.index <= self._last_index:
                # overwrite: invalidate the stale tail; rewind last_written
                # to the real predecessor term so AER replies stay truthful
                for k in range(entry.index + 1, self._last_index + 1):
                    self._memtable.pop(k, None)
                    self._mem_bytes.pop(k, None)
                if self._last_written.index >= entry.index:
                    self._last_written = IdxTerm(entry.index - 1,
                                                 rewind_term)
            self._memtable[entry.index] = entry
            self._mem_bytes[entry.index] = payload
            self._last_index = entry.index
            self._last_term = entry.term
            truncate = self._truncate_next
            self._truncate_next = False
            # submit under the log lock (queue.put only — no blocking):
            # wal_restarted() holds the same lock across its resend batch,
            # so a live append can never slip into the restarted WAL's
            # queue AHEAD of the resends of a durability hole below it.
            # Generation guard: a restarted WAL resets the per-writer
            # sequence check, so a first write racing the SUPERVISOR'S
            # resend hook would be accepted ABOVE a durability hole — if
            # the WAL then dies again before the resend lands, the
            # on-disk log has a committed-looking tail over a missing
            # middle (a log that could win elections it must lose).
            # Resend-before-submit closes the window; the supervisor's
            # later call is an idempotent no-op for covered entries.
            if getattr(self, "_wal_generation", None) != \
                    self.wal.generation:
                self._resend_unconfirmed_locked()
            self.wal.write(self.uid, entry.index, entry.term, payload,
                           truncate=truncate)

    def set_last_index(self, idx: int) -> None:
        if idx >= self._last_index:
            return
        # pre-read OUTSIDE the log lock (rule RA11, the _put/_put_batch
        # idiom): a fetch_term miss under _lock would take _io_lock and
        # invert the documented io-then-log order.  Race-free: terms
        # are immutable at a given index until overwritten, and only
        # the event-loop thread that calls this truncates/overwrites.
        term = self.fetch_term(idx) or 0
        with self._lock:
            if idx >= self._last_index:
                return
            for i in range(idx + 1, self._last_index + 1):
                self._memtable.pop(i, None)
                self._mem_bytes.pop(i, None)
            self._last_index, self._last_term = idx, term
            if self._last_written.index > idx:
                self._last_written = IdxTerm(idx, term)

    def reset_to_last_known_written(self) -> None:
        self.set_last_index(self._last_written.index)

    # -- events -------------------------------------------------------------

    def take_events(self) -> list:
        with self._lock:
            evts, self._events = self._events, []
        return evts

    def handle_written(self, evt: WrittenEvent,
                       _seg: tuple = (None, None)) -> None:
        with self._lock:
            if evt.from_index > self._last_written.index + 1 and \
                    evt.from_index <= self._last_index:
                # contiguity guard: a confirm above a durability hole
                # (e.g. an append that raced a post-crash resend) must not
                # advance last_written past entries no WAL file holds.
                # An index in (last_written, from_index) that has LEFT the
                # memtable is already durable — the only exits are a
                # segment flush or a snapshot truncation — so only
                # memtable-resident hole entries need a resend; if there
                # are none, the confirm is safe to accept as-is.
                first_resident = next(
                    (i for i in range(self._last_written.index + 1,
                                      evt.from_index)
                     if i in self._mem_bytes), None)
                if first_resident is not None:
                    # drop the confirm and resend the resident span up to
                    # to_index so confirms re-arrive contiguously
                    # (ra_log's written-event ordering invariant,
                    # ra_log.erl:474-529)
                    for idx in range(first_resident, evt.to_index + 1):
                        ent = self._memtable.get(idx)
                        raw = self._mem_bytes.get(idx)
                        if ent is not None and raw is not None:
                            self.counters["write_resends"] += 1
                            self.wal.write(self.uid, idx, ent.term, raw)
                    return
            if evt.from_index > self._last_index:
                # reverted below the whole range (explicit reset or
                # snapshot install raced the WAL): stale, drop
                # (ra_log.erl:474-481)
                return
            # clamp the confirm to the current tail BEFORE the term
            # check (ra_log.erl:495 ToIdx = min(ToIdx0, LastIdx)): a
            # coalesced batch confirm can cover an overwritten suffix
            # while its surviving prefix is genuinely durable
            to = min(evt.to_index, self._last_index)
            if to <= self._last_written.index:
                # duplicate/stale confirm: every branch below is a
                # no-op for an index already at/under last_written
                return
            term = self._mem_term_locked(to)
            if term is _MISS and _seg[0] == to:
                # resolved by the out-of-lock segment read below
                term = _seg[1]
            if term is not _MISS:
                if term == evt.term:
                    # to > last_written is guaranteed by the early
                    # return above (the lock is held throughout)
                    self._last_written = IdxTerm(to, term)
                elif term is None and self._snapshot is not None and \
                        self._snapshot[0].index >= to:
                    pass  # truncated by snapshot: subsumed
                # else: stale confirm for an overwritten term — ignored;
                # the rewrite is already queued to the WAL
                return
        # Memtable miss ABOVE last_written: the entry was flushed +
        # pruned to a segment before this confirm was processed — the
        # segment writer flushes up to the WAL FILE's range, which can
        # run ahead of the log's processed confirm watermark, so this
        # is a valid confirm for an already-segment-durable entry and
        # must still advance last_written.  Resolve the term WITHOUT
        # holding _lock (a segment read takes _io_lock; io-then-log is
        # the documented order, rule RA11) and re-enter: ``to`` is
        # stable across the round trip — evt is ours and _last_index
        # only moves on this event-loop thread — so the second pass
        # hits the ``_seg[0] == to`` branch and terminates.
        got = self._segment_read(to)
        self.handle_written(evt, _seg=(to, got[0] if got else None))

    # -- reads --------------------------------------------------------------

    def fetch(self, idx: int) -> Optional[Entry]:
        self.counters["read_ops"] += 1
        with self._lock:
            # entries at/below the snapshot index are truncated even when a
            # partially-covered segment still holds bytes for them
            if idx < self._first_index or idx > self._last_index:
                return None
            ent = self._memtable.get(idx)
            if ent is not None:
                self.counters["read_cache"] += 1
                return ent
        got = self._segment_read(idx)
        if got is None:
            return None
        term, payload = got
        return Entry(idx, term, decode_command(payload))

    def _segment_read(self, idx: int) -> Optional[tuple]:
        with self._io_lock:
            for seg in reversed(self._segments):
                r = seg.range()
                if r and r[0] <= idx <= r[1]:
                    self._open_segments.touch(seg.path, seg)
                    got = seg.read(idx)
                    if got is not None:
                        self.counters["read_segment"] += 1
                        return got
        return None

    def _mem_term_locked(self, idx: int):
        """Memtable/snapshot half of a term lookup; MUST run under
        self._lock.  Returns ``_MISS`` when only a segment read can
        answer — callers holding _lock must NOT fall through to
        ``_segment_read`` (it takes _io_lock; io-then-log is the
        documented order, rule RA11)."""
        if self._snapshot is not None and \
                idx == self._snapshot[0].index:
            return self._snapshot[0].term
        if idx < self._first_index or idx > self._last_index:
            return None
        ent = self._memtable.get(idx)
        if ent is not None:
            return ent.term
        return _MISS

    def fetch_term(self, idx: int) -> Optional[int]:
        self.counters["fetch_term"] += 1
        with self._lock:
            got = self._mem_term_locked(idx)
        if got is not _MISS:
            return got
        got = self._segment_read(idx)
        return got[0] if got else None

    def exists(self, idx: int, term: int) -> bool:
        return self.fetch_term(idx) == term

    def fold(self, from_idx: int, to_idx: int, fn: Callable,
             acc: Any) -> Any:
        for e in self.read_range(from_idx, to_idx):
            acc = fn(e, acc)
        return acc

    def read_range(self, from_idx: int, to_idx: int) -> list:
        """Batched range read: ONE lock cycle for the memtable pass
        (the hot case — AER building and the apply fold read recent
        entries), with per-index segment fallback for anything older
        (ISSUE 13; the per-index ``fetch`` path paid a lock per
        entry)."""
        out: list = []
        misses = 0
        with self._lock:
            lo = max(from_idx, self._first_index)
            hi = min(to_idx, self._last_index)
            if hi < lo:
                return out
            n = hi - lo + 1
            self.counters["read_ops"] += n
            mt = self._memtable
            for i in range(lo, hi + 1):
                ent = mt.get(i)
                if ent is not None:
                    out.append(ent)    # the stored Entry, no rebuild
                else:
                    out.append(i)  # placeholder: resolve via segments
                    misses += 1
            self.counters["read_cache"] += n - misses
        if misses:
            for k, v in enumerate(out):
                if type(v) is int:
                    got = self._segment_read(v)
                    out[k] = Entry(v, got[0], decode_command(got[1])) \
                        if got is not None else None
            out = [e for e in out if e is not None]
        return out

    def read_range_with_payloads(self, from_idx: int, to_idx: int,
                                 max_bytes: int = 0) -> Optional[tuple]:
        """(entries, payloads) for the memtable-resident contiguous
        prefix of [from_idx, to_idx] — the leader's AER build reads
        entries AND their already-encoded durable images in one lock
        cycle, so followers can feed their WAL without re-encoding
        (AppendEntriesRpc.payloads, ISSUE 13).  ``max_bytes`` > 0 caps
        the prefix at the frame byte budget.  None when the range head
        has left the memtable (segment-flushed catch-up) — the caller
        falls back to ``read_range`` with no payloads."""
        entries: list = []
        payloads: list = []
        total = 0
        with self._lock:
            if from_idx < self._first_index or \
                    to_idx > self._last_index or to_idx < from_idx:
                return None
            mt = self._memtable
            mb = self._mem_bytes
            for i in range(from_idx, to_idx + 1):
                ent = mt.get(i)
                raw = mb.get(i)
                if ent is None or raw is None:
                    break
                entries.append(ent)
                payloads.append(raw)
                total += len(raw)
                if max_bytes and total >= max_bytes:
                    break
            n = len(entries)
            self.counters["read_ops"] += n
            self.counters["read_cache"] += n
        if not entries:
            return None
        return entries, payloads

    def sparse_read(self, indexes: Iterable[int]) -> list:
        out = []
        for i in indexes:
            e = self.fetch(i)
            if e is not None:
                out.append(e)
        return out

    # -- meta ---------------------------------------------------------------

    def store_meta(self, sync: bool = True, **kv: Any) -> None:
        """Durable meta store.  term/voted_for fsync before the call
        returns (MUST hit disk before vote replies; stricter than the
        reference's batched ra_log_meta — votes are rare).  The lazy
        last_applied watermark passes sync=False: atomic replace without
        fsync, since losing it only costs effect-dedup precision."""
        with self._lock:
            self._meta.update(kv)
            data = pickle.dumps(self._meta)
        tmp = os.path.join(self.dir, "meta.partial")
        with open(tmp, "wb") as f:
            f.write(data)
            if sync:
                # rides the storage shim ("meta" fault class); an EIO
                # here MUST propagate — a vote reply over an unsynced
                # term/voted_for is the double-vote hazard
                f.flush()
                IO.sync(f.fileno(), 2, path_class="meta")
        os.replace(tmp, os.path.join(self.dir, "meta"))

    def fetch_meta(self, key: str, default: Any = None) -> Any:
        return self._meta.get(key, default)

    # -- segment flush (called by the SegmentWriter thread) -----------------

    def flush_mem_to_segments(self, up_to: int) -> tuple:
        """Drain the memtable to segment files; returns
        ``(entries, bytes, segments_created)`` for the segment writer's
        counters (ra_log_segment_writer.erl:37-52)."""
        with self._io_lock:
            with self._lock:
                snap_idx = self._snapshot[0].index if self._snapshot else 0
                items = sorted((i, self._mem_bytes[i],
                                self._memtable[i].term)
                               for i in self._mem_bytes
                               if i <= up_to and i > snap_idx
                               and i <= self._last_index)
                seq_before = self._seg_seq
            # skip entries already segment-durable with an AGREEING term
            # (e.g. recovered duplicates from a retained stale WAL file):
            # re-appending one at a lower index would trip the segment's
            # overwrite-invalidation (append ≤ existing wipes everything
            # above) and destroy durable entries the memtable no longer
            # holds.  A term MISMATCH is a genuine overwrite and must
            # still go through — invalidating the stale tail is then the
            # point.  (Inline segment scan: _io_lock is already held.)
            write_items = items
            if items:
                def _seg_term(idx: int):
                    for seg in reversed(self._segments):
                        r = seg.range()
                        if r and r[0] <= idx <= r[1]:
                            got = seg.read(idx)
                            if got is not None:
                                return got[0]
                    return None
                seg_hi = max((seg.range()[1] for seg in self._segments
                              if seg.range() is not None), default=0)
                write_items = [(i, p, t) for i, p, t in items
                               if i > seg_hi or _seg_term(i) != t]
            nbytes = 0
            if write_items:
                seg = self._current_segment()
                self._open_segments.touch(seg.path, seg)
                for idx, payload, term in write_items:
                    if not seg.append(idx, term, payload):
                        seg.flush()
                        seg = self._new_segment()
                        self._open_segments.touch(seg.path, seg)
                        seg.append(idx, term, payload)
                    nbytes += len(payload)
                seg.flush()
            with self._lock:
                # ra swaps memtable for segment refs (:534-574): drop both
                # copies; reads now resolve via the segment files.  The
                # skipped duplicates prune too — they are ALREADY durable
                # in a segment, which is what the prune asserts.
                for idx, _, _ in items:
                    self._mem_bytes.pop(idx, None)
                    self._memtable.pop(idx, None)
                return (len(write_items), nbytes,
                        self._seg_seq - seq_before)

    def _current_segment(self) -> SegmentFile:
        with self._lock:
            if self._segments and not self._segments[-1].full:
                return self._segments[-1]
            return self._new_segment()

    def _new_segment(self) -> SegmentFile:
        with self._lock:
            self._seg_seq += 1
            path = os.path.join(self.dir, f"{self._seg_seq:08d}.segment")
            seg = SegmentFile(path, self.segment_max_count, create=True)
            self._segments.append(seg)
            self._open_segments.touch(seg.path, seg)
            return seg

    # -- snapshots ----------------------------------------------------------

    def snapshot_index_term(self) -> IdxTerm:
        if self._snapshot is None:
            return IdxTerm(0, 0)
        m = self._snapshot[0]
        return IdxTerm(m.index, m.term)

    def snapshot_meta(self):
        """The current snapshot's metadata (in-memory; no data read)."""
        with self._lock:
            return self._snapshot[0] if self._snapshot is not None \
                else None

    def checkpoint_index(self) -> int:
        """Newest checkpoint index, 0 if none (the checkpoint_index
        gauge, ra.hrl:378)."""
        with self._lock:
            return self._checkpoints[-1][0].index if self._checkpoints \
                else 0

    def snapshot(self) -> Optional[tuple]:
        """(meta, data_bytes) of the current snapshot, for chunked send."""
        if self._snapshot is None:
            return None
        meta, path = self._snapshot
        got = _read_snapshot_file(path)
        if got is None:
            return None
        return meta, got[1]

    def update_release_cursor(self, idx: int, cluster: tuple,
                              machine_version: int,
                              machine_state: Any) -> list:
        term = self.fetch_term(idx)
        if term is None:
            return []
        meta = SnapshotMeta(index=idx, term=term, cluster=cluster,
                            machine_version=machine_version)
        path = os.path.join(self.dir, "snapshot",
                            f"snap_{idx:016d}_{term:010d}.rtsn")
        data = self.snapshot_module.encode(machine_state)
        try:
            _write_snapshot_file(path, meta, data)
        except OSError:
            # degradation: the release cursor simply does not advance —
            # the old snapshot and the full log stay intact (pending-dir
            # discipline), and a later release point retries
            _fault_note("snapshot_write_failures")
            _drop_partial(path)
            return []
        self.counters["snapshots_written"] += 1
        self.counters["snapshot_bytes_written"] += len(data)
        old = self._snapshot
        with self._lock:
            self._snapshot = (meta, path)
        self._truncate_to(idx)
        if old is not None and old[1] != path:
            try:
                os.unlink(old[1])
            except FileNotFoundError:
                pass
        self._drop_stale_checkpoints(idx)
        return []

    def checkpoint(self, idx: int, cluster: tuple, machine_version: int,
                   machine_state: Any) -> list:
        term = self.fetch_term(idx)
        if term is None:
            return []
        meta = SnapshotMeta(index=idx, term=term, cluster=cluster,
                            machine_version=machine_version)
        path = os.path.join(self.dir, "checkpoints",
                            f"cp_{idx:016d}_{term:010d}.rtsn")
        data = self.snapshot_module.encode(machine_state)
        try:
            _write_snapshot_file(path, meta, data)
        except OSError:
            # a checkpoint is purely a replay shortcut: skipping a
            # failed one loses nothing (the log is untouched)
            _fault_note("snapshot_write_failures")
            _drop_partial(path)
            return []
        self.counters["checkpoints_written"] += 1
        self.counters["checkpoint_bytes_written"] += len(data)
        with self._lock:
            self._checkpoints.append((meta, path))
            # retention (ra.hrl:234 + take_older_checkpoints)
            while len(self._checkpoints) > MAX_CHECKPOINTS:
                _, old_path = self._checkpoints.pop(0)
                try:
                    os.unlink(old_path)
                except FileNotFoundError:
                    pass
        return []

    def promote_checkpoint(self, idx: int) -> bool:
        """Rename the newest checkpoint <= idx into the snapshot slot
        (ra_snapshot.erl:399-448)."""
        with self._lock:
            best = None
            for meta, path in self._checkpoints:
                if meta.index <= idx and \
                        (best is None or meta.index > best[0].index):
                    best = (meta, path)
            if best is None:
                return False
            self._checkpoints = [c for c in self._checkpoints
                                 if c[0].index > best[0].index]
        meta, cp_path = best
        snap_path = os.path.join(
            self.dir, "snapshot",
            f"snap_{meta.index:016d}_{meta.term:010d}.rtsn")
        self.counters["checkpoints_promoted"] += 1
        os.replace(cp_path, snap_path)
        old = self._snapshot
        with self._lock:
            self._snapshot = (meta, snap_path)
        self._truncate_to(meta.index)
        if old is not None:
            try:
                os.unlink(old[1])
            except FileNotFoundError:
                pass
        return True

    def install_snapshot(self, meta: SnapshotMeta, data: bytes) -> None:
        path = os.path.join(self.dir, "snapshot",
                            f"snap_{meta.index:016d}_{meta.term:010d}.rtsn")
        try:
            _write_snapshot_file(path, meta, data)
        except OSError:
            # the install must FAIL loudly (the leader retries the
            # transfer); the torn .partial never reached the slot
            _fault_note("snapshot_write_failures")
            _drop_partial(path)
            raise
        self._post_install(meta, path)

    def _post_install(self, meta: SnapshotMeta, path: str) -> None:
        """Swap in a freshly written snapshot file and truncate the log
        below it (shared by whole-buffer and streamed installs)."""
        self.counters["snapshot_installed"] += 1
        old = self._snapshot
        with self._lock:
            self._snapshot = (meta, path)
            if self._last_index < meta.index:
                self._last_index = meta.index
                self._last_term = meta.term
            if self._last_written.index <= meta.index:
                self._last_written = IdxTerm(meta.index, meta.term)
            # the next follower write after an install truncates the WAL
            # stream (wal_truncate_write, ra_log.erl:303,1033)
            self._truncate_next = True
        self._truncate_to(meta.index)
        if old is not None and old[1] != path:
            try:
                os.unlink(old[1])
            except FileNotFoundError:
                pass

    # -- chunk-incremental snapshot accept (ra_snapshot.erl:465-508,
    # ra_log_snapshot.erl:73-111): chunks stream to a .partial file with
    # per-chunk crc validation and O(chunk) memory; the assembled body
    # crc is patched into the header on the last chunk and the file
    # swapped in atomically --------------------------------------------

    def begin_accept(self, meta: SnapshotMeta) -> None:
        """Open a fresh accept stream (chunk 1 of an install).  A
        restarted install simply begins again — the .partial truncates."""
        self.abort_accept()
        path = os.path.join(self.dir, "snapshot", "accept.partial")
        f = open(path, "wb")
        meta_b = pickle.dumps(meta)
        prefix = struct.pack("<I", len(meta_b)) + meta_b
        # crc slot written as 0 now, patched in complete_accept
        f.write(_SNAP_HDR.pack(SNAP_MAGIC, 1, 0) + prefix)
        self._accept = {"meta": meta, "path": path, "f": f,
                        "crc": IO.crc32(prefix), "chunks": 0}

    def accept_chunk(self, data: bytes, chunk_number: int,
                     chunk_crc: int = -1) -> bool:
        """Append one chunk; False = validation failure (caller aborts
        the install and the leader restarts it)."""
        a = getattr(self, "_accept", None)
        if a is None:
            return False
        if chunk_number == 1 and a["chunks"] > 0:
            # same-snapshot transfer restarted from the top (sender
            # retry): truncate the stream rather than double-append
            self.begin_accept(a["meta"])
            a = self._accept
        if chunk_crc >= 0 and IO.crc32(data) != chunk_crc:
            self.abort_accept()
            return False
        a["f"].write(data)
        a["crc"] = zlib.crc32(data, a["crc"])
        a["chunks"] += 1
        return True

    def complete_accept(self) -> bool:
        """Finalize the stream: patch the body crc into the header, fsync,
        atomically rename into the snapshot slot, truncate the log."""
        a = getattr(self, "_accept", None)
        if a is None:
            return False
        self._accept = None
        f, meta = a["f"], a["meta"]
        try:
            f.seek(8)  # crc field of _SNAP_HDR (<4sII)
            f.write(struct.pack("<I", a["crc"]))
            f.flush()
            IO.sync(f.fileno(), 2, path_class="snapshot")
        except OSError:
            # the stream never reached the snapshot slot: drop the
            # .partial and report failure — the leader restarts the
            # transfer from chunk 1
            _fault_note("snapshot_write_failures")
            self._accept = a
            self.abort_accept()
            return False
        f.close()
        path = os.path.join(self.dir, "snapshot",
                            f"snap_{meta.index:016d}_{meta.term:010d}.rtsn")
        os.replace(a["path"], path)
        self._post_install(meta, path)
        return True

    def abort_accept(self) -> None:
        """Drop an in-flight accept stream (leader change / timeout /
        corrupt chunk)."""
        a = getattr(self, "_accept", None)
        self._accept = None
        if a is not None:
            try:
                a["f"].close()
            except OSError:
                # safe to swallow: the stream is being abandoned — its
                # bytes are garbage by definition (the leader restarts
                # the transfer), so a failed close loses nothing
                _fault_note("swallowed_oserrors")
            try:
                os.unlink(a["path"])
            except OSError:
                # safe to swallow: a stranded accept.partial can never
                # shadow a real snapshot (recovery unlinks it at boot,
                # _recover_state) — it only leaks bytes until then
                _fault_note("swallowed_oserrors")

    def recover_snapshot_state(self) -> Optional[tuple]:
        if self._snapshot is None:
            return None
        meta, path = self._snapshot
        got = _read_snapshot_file(path)
        if got is None:
            return None  # torn/corrupt container: fall back to older
        if not self.snapshot_module.validate(got[1]):
            # a crc-valid container the selected module rejects is a
            # FORMAT mismatch (e.g. module changed without migration):
            # re-initializing machine state over a truncated log would
            # be silent divergence — fail loudly instead
            raise ValueError(
                f"snapshot {path} rejected by snapshot module "
                f"{self.snapshot_module.name!r} (format mismatch?)")
        return meta, self.snapshot_module.decode(got[1])

    def recover_machine_base(self) -> Optional[tuple]:
        """Newest valid machine-state base among the snapshot and the
        retained checkpoints (ra_snapshot:init picks the latest valid
        image, ra_snapshot.erl:183-222; the recover_from_checkpoint_*
        cases of ra_checkpoint_SUITE).  Checkpoints do not truncate the
        log, so recovering from one is purely a replay shortcut; corrupt
        or undecodable checkpoints fall back to the next older image."""
        with self._lock:
            cps = list(self._checkpoints)
            snap_idx = self._snapshot[0].index if self._snapshot else -1
        for meta, path in reversed(cps):        # newest first
            if meta.index <= snap_idx:
                break  # snapshot is newer: no need to read checkpoints
            got = _read_snapshot_file(path)
            if got is None or not self.snapshot_module.validate(got[1]):
                continue  # torn/corrupt container: try the next older
            try:
                state = self.snapshot_module.decode(got[1])
            except Exception:
                continue
            return meta, state
        # no usable checkpoint above the snapshot: decode the snapshot
        # (deferred until here — a superseding checkpoint must not pay a
        # full snapshot read+decode)
        return self.recover_snapshot_state()

    def snapshot_data(self) -> bytes:
        got = self.snapshot()
        assert got is not None
        return got[1]

    def _truncate_to(self, idx: int) -> None:
        """Drop memtable entries and whole segments covered by a snapshot
        (delete_segments, ra_log.erl:1010).  Takes the io lock so an
        in-flight segment flush never races the close/unlink."""
        with self._io_lock:
            with self._lock:
                for i in [i for i in self._memtable if i <= idx]:
                    self._memtable.pop(i, None)
                    self._mem_bytes.pop(i, None)
                self._first_index = idx + 1
                keep = []
                victims = []
                for seg in self._segments:
                    r = seg.range()
                    if r is not None and r[1] <= idx:
                        victims.append(seg)
                    else:
                        keep.append(seg)
                self._segments = keep
                # a kept segment holding slots above last_index is a stale
                # overwritten tail; once the snapshot swallows the WAL's
                # truncation record this segment would be the only durable
                # "evidence" for those indexes — truncate it physically
                for seg in keep:
                    r = seg.range()
                    if r is not None and r[1] > self._last_index:
                        seg.truncate_from(self._last_index + 1)
            for seg in victims:
                self._open_segments.pop(seg.path)
                if self._readers:
                    # external readers hold the pre-truncation view: move
                    # the segment to the pinned list instead of deleting
                    # (the reference defers its memtable/segment deletion
                    # while registered readers exist, ra_log.erl:534-574)
                    self._pinned_segments.append(seg)
                else:
                    seg.close()
                    try:
                        os.unlink(seg.path)
                    except FileNotFoundError:
                        pass

    def _drop_stale_checkpoints(self, idx: int) -> None:
        with self._lock:
            stale = [c for c in self._checkpoints if c[0].index <= idx]
            self._checkpoints = [c for c in self._checkpoints
                                 if c[0].index > idx]
        for _, path in stale:
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass

    # -- external readers (ra_log.erl:983-1008) -----------------------------

    def register_reader(self, name: str) -> "LogReader":
        """Register an external reader over the segment-flushed portion of
        the log.  While any reader is registered, snapshot truncation
        defers segment deletion (the files move to a pinned list the
        readers can still resolve) — the role the reference fills with
        deferred ETS/segment deletion for registered readers.

        Registration takes _io_lock so it serialises against an in-flight
        _truncate_to: without it a truncation could re-read self._readers
        mid-victims-loop and unlink segments a reader registered between
        iterations could already see."""
        with self._io_lock:
            with self._lock:
                self._readers[name] = self._readers.get(name, 0) + 1
        return LogReader(self, name)

    def close_reader(self, name: str) -> None:
        with self._io_lock:
            with self._lock:
                n = self._readers.get(name, 0) - 1
                if n > 0:
                    self._readers[name] = n
                else:
                    self._readers.pop(name, None)
                if self._readers:
                    return
                victims, self._pinned_segments = self._pinned_segments, []
            for seg in victims:
                self._open_segments.pop(seg.path)
                seg.close()
                try:
                    os.unlink(seg.path)
                except FileNotFoundError:
                    pass

    def _reader_read(self, idx: int) -> Optional[tuple]:
        """Resolve an index for an external reader: live segments first,
        then segments pinned past a truncation."""
        with self._io_lock:
            # newest wins: live segments (newer) before pinned (older,
            # pre-truncation) — so the concat is pinned first, reversed
            for seg in reversed(self._pinned_segments + self._segments):
                r = seg.range()
                if r and r[0] <= idx <= r[1]:
                    # reader reads respect the fd cap too: an untracked
                    # reopen would defeat MAX_OPEN_SEGMENTS over a long
                    # fold (pinned segments share the same cache)
                    self._open_segments.touch(seg.path, seg)
                    got = seg.read(idx)
                    if got is not None:
                        return got
        return None

    # -- misc ---------------------------------------------------------------

    def tick(self, now_ms: float) -> list:
        return []

    def close(self) -> None:
        # _io_lock first: a SegmentWriter flush in flight must finish
        # before fds close, or its pwrites could land on a recycled fd
        # number belonging to an unrelated file
        with self._io_lock:
            with self._lock:
                self._open_segments.evict_all()
                for seg in self._segments + self._pinned_segments:
                    seg.close()

    def overview(self) -> dict:
        return {
            "type": "durable",
            "uid": self.uid,
            "last_index": self._last_index,
            "last_term": self._last_term,
            "first_index": self._first_index,
            "last_written_index_term": tuple(self._last_written),
            "num_mem_entries": len(self._memtable),
            "num_segments": len(self._segments),
            "snapshot_index_term": tuple(self.snapshot_index_term()),
            "num_checkpoints": len(self._checkpoints),
        }
