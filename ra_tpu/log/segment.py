"""Per-server segment files + the node-wide segment writer.

Segment format follows the shape of the reference's (ra_log_segment.erl:
30-43: magic, version, preallocated fixed-capacity index region, data
region; entries carry crc32) with our own layout:

  header:  magic "RTSG"(4) | version:u32 | max_count:u32 | reserved:u32
  index:   max_count slots of (idx:u64 term:u64 offset:u64 len:u32 crc:u32)
  data:    payloads

Appends buffer in memory and reach disk in one pwrite-per-region + fsync
flush (append/sync, ra_log_segment.erl:175-266).  A slot with idx 0 is
empty (real indexes are >= 1).

The SegmentWriter is the node-wide drain: the WAL hands it per-server
ranges on rollover; it flushes each server's memtable to that server's
segment files, notifies the server's log, and deletes the WAL file once
every server's flush is done (ra_log_segment_writer.erl:129-201,
accept_mem_tables/truncate_segments roles).
"""
from __future__ import annotations

import os
import struct
import threading
import time
import queue
from typing import Callable, Optional

from .faults import IO, note as _fault_note

MAGIC = b"RTSG"
_HDR = struct.Struct("<4sIII")
_SLOT = struct.Struct("<QQQII")
DEFAULT_MAX_COUNT = 4096  # entries per segment (ra.hrl:202)


class SegmentFile:
    """One append-optimized segment file."""

    def __init__(self, path: str, max_count: int = DEFAULT_MAX_COUNT,
                 create: bool = False) -> None:
        self.path = path
        self.max_count = max_count
        self.index: dict[int, tuple] = {}  # idx -> (term, offset, len, crc)
        self._pending: list = []           # [(idx, term, payload)]
        self._count = 0
        self._max_idx = 0  # highest live-or-pending index (0 = empty)
        if create:
            self.fd = IO.random_open(path, truncate=True)
            hdr = _HDR.pack(MAGIC, 1, max_count, 0)
            IO.pwrite(self.fd, hdr + b"\x00" * (_SLOT.size * max_count), 0)
            self._data_off = _HDR.size + _SLOT.size * max_count
            self._next_off = self._data_off
        else:
            self.fd = IO.random_open(path)
            self._load()

    def _ensure_open(self) -> int:
        """Reopen the fd after a close_fd() eviction; the in-memory index
        is kept, so reopening is just an open(2)."""
        if self.fd is None:
            self.fd = IO.random_open(self.path)
        return self.fd

    def close_fd(self) -> None:
        """Close only the descriptor (LRU eviction by the log's open-
        segment cache, the ra_flru role); the index stays loaded and any
        read/flush transparently reopens."""
        if self.fd is not None:
            IO.close(self.fd)
            self.fd = None

    def _load(self) -> None:
        hdr = IO.pread(self.fd, _HDR.size, 0)
        magic, version, max_count, _ = _HDR.unpack(hdr)
        if magic != MAGIC:
            raise ValueError(f"bad segment magic in {self.path}")
        self.max_count = max_count
        self._data_off = _HDR.size + _SLOT.size * max_count
        raw = IO.pread(self.fd, _SLOT.size * max_count, _HDR.size)
        self._next_off = self._data_off
        for i in range(max_count):
            idx, term, off, ln, crc = _SLOT.unpack_from(raw, i * _SLOT.size)
            if idx == 0:
                break
            # slots are strictly append-ordered, so a slot that rewrites a
            # lower index is an overwrite: it invalidates every entry above
            # it written earlier (same dedup as WAL recovery — a stale
            # tail must not survive a reload)
            self._invalidate_from(idx)
            self.index[idx] = (term, off, ln, crc)
            self._max_idx = max(self._max_idx, idx)
            self._count += 1
            self._next_off = max(self._next_off, off + ln)

    # -- write side ---------------------------------------------------------

    def _invalidate_from(self, idx: int) -> None:
        """Drop every live/pending entry at/above ``idx`` — the single
        slot-order dedup shared by live appends and reload (_load), so
        the live index can never disagree with what a reload would
        reconstruct.  Fast path: a strictly-ascending append (the flush
        hot path) skips the sweep entirely via the max-index watermark."""
        if idx > self._max_idx:
            return
        for k in [k for k in self.index if k >= idx]:
            del self.index[k]
        self._pending = [p for p in self._pending if p[0] < idx]
        self._max_idx = max(max(self.index, default=0),
                            max((p[0] for p in self._pending), default=0))

    def append(self, idx: int, term: int, payload: bytes) -> bool:
        """Buffer an entry; False when the segment is full
        ({error, full} in the reference).  Appending at-or-below an
        existing index is an overwrite: it invalidates every LIVE entry
        at/above it immediately (see _invalidate_from)."""
        # capacity already in the FILE is append-only: refuse before
        # touching any state, so a refused append never makes the live
        # index disagree with what a reload reconstructs
        if self._count >= self.max_count:
            return False
        # invalidate BEFORE the pending-capacity check: an overwrite
        # landing in a segment whose capacity is consumed by PENDING
        # entries frees the superseded tail and fits in place instead of
        # forcing a roll.  A refusal below cannot follow a mutation: it
        # requires no pending ≥ idx (freeing even one slot admits this
        # append), and live flushed entries ≥ idx with all pending < idx
        # cannot coexist (the lower-idx pending append already swept
        # that flushed tail) — so on the refusal path the invalidation
        # was the _max_idx fast-path no-op.
        self._invalidate_from(idx)
        if self._count + len(self._pending) >= self.max_count:
            return False
        self._pending.append((idx, term, payload))
        self._max_idx = max(self._max_idx, idx)
        return True

    def flush(self) -> None:
        """Write pending data + index slots, then fsync (sync/flush,
        ra_log_segment.erl:222-266)."""
        if not self._pending:
            return
        self._ensure_open()
        data = bytearray()
        slots = bytearray()
        off = self._next_off
        base_slot = self._count
        staged = []
        for idx, term, payload in self._pending:
            crc = IO.crc32(payload)
            staged.append((idx, (term, off, len(payload), crc)))
            slots += _SLOT.pack(idx, term, off, len(payload), crc)
            data += payload
            off += len(payload)
        # NB: on an I/O error below, NO in-memory bookkeeping changes —
        # index/_count/_next_off/_pending stay exactly retry-shaped, so
        # a retried flush re-issues the SAME pwrites at the same offsets
        # (idempotent) and re-dirties the pages a failed fsync may have
        # dropped — which is why retrying the fsync here, unlike on a
        # WAL fd, is safe.  The index commits only AFTER the fsync:
        # readers (and the flush-side already-durable filter) must never
        # see written-but-unsynced slots as durable entries.
        IO.pwrite(self.fd, bytes(data), self._next_off)
        IO.pwrite(self.fd, bytes(slots),
                  _HDR.size + base_slot * _SLOT.size)
        IO.sync(self.fd, 2)
        for idx, ent in staged:
            self.index[idx] = ent
        self._count += len(self._pending)
        self._next_off = off
        self._pending.clear()

    def truncate_from(self, idx: int) -> None:
        """Durably drop every entry >= idx.  Used when a snapshot makes an
        overwritten segment tail the only remaining durable record of
        those indexes — it must not resurrect on reload.  The surviving
        entries are rewritten to a fresh file swapped in with an atomic
        rename: an in-place slot-region rewrite would break the
        append-only crash discipline (a torn rewrite could interleave new
        and old slot layouts, resurrecting — or corrupting — the tail).
        Rare (snapshot-covering-an-overwrite only), so the copy is
        acceptable."""
        self._pending = [p for p in self._pending if p[0] < idx]
        stale = [k for k in self.index if k >= idx]
        if not stale:
            return
        survivors = [(k, self.index[k][0], self.read(k)[1])
                     for k in sorted(self.index) if k < idx]
        tmp_path = self.path + ".trunc"
        fresh = SegmentFile(tmp_path, self.max_count, create=True)
        for k, term, payload in survivors:
            fresh.append(k, term, payload)
        fresh.flush()
        IO.sync(fresh.fd, 2)  # flush() early-returns when there are no
        fresh.close()         # survivors; the header must still be durable
        self.close_fd()
        os.replace(tmp_path, self.path)
        self.fd = IO.random_open(self.path)
        self.index = {}
        self._pending = []
        self._count = 0
        self._max_idx = 0
        self._load()

    # -- read side ----------------------------------------------------------

    def read(self, idx: int) -> Optional[tuple]:
        """Returns (term, payload) with crc verification
        (ra_log_segment.erl:268-335).  A crc mismatch is retried ONCE
        with a fresh pread — transient read-side corruption (bit rot in
        flight, an injected fault) must not take down a reader when the
        on-disk bytes are fine; a second mismatch is real damage and
        raises."""
        ent = self.index.get(idx)
        if ent is None:
            return None
        term, off, ln, crc = ent
        payload = IO.pread(self._ensure_open(), ln, off)
        if IO.crc32(payload) != crc:
            _fault_note("crc_catches")
            payload = IO.pread(self._ensure_open(), ln, off)
            if IO.crc32(payload) != crc:
                raise ValueError(
                    f"segment crc mismatch at {idx} in {self.path}")
        return term, payload

    def range(self) -> Optional[tuple]:
        if not self.index:
            return None
        return min(self.index), max(self.index)

    @property
    def full(self) -> bool:
        return self._count + len(self._pending) >= self.max_count

    def close(self) -> None:
        if self.fd is not None:
            IO.close(self.fd)
            self.fd = None


class _DaemonFuture:
    __slots__ = ("_done", "_result", "_exc")

    def __init__(self) -> None:
        self._done = threading.Event()
        self._result = None
        self._exc: Optional[BaseException] = None

    def result(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError("flush worker stalled")
        if self._exc is not None:
            raise self._exc
        return self._result


class _DaemonPool:
    """Minimal daemon-thread worker pool (submit -> future)."""

    def __init__(self, workers: int, name: str) -> None:
        self._queue: "queue.Queue" = queue.Queue()
        self._workers = workers
        for i in range(workers):
            t = threading.Thread(target=self._work, daemon=True,
                                 name=f"{name}-{i}")
            t.start()

    def stop(self) -> None:
        for _ in range(self._workers):
            self._queue.put(None)

    def _work(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            fn, args, fut = item
            try:
                fut._result = fn(*args)
            except BaseException as exc:  # noqa: BLE001 — carried to result()
                fut._exc = exc
            fut._done.set()

    def submit(self, fn, *args) -> _DaemonFuture:
        fut = _DaemonFuture()
        self._queue.put((fn, args, fut))
        return fut


class SegmentWriter:
    """Node-wide background flusher: WAL rollover ranges -> segment files.

    Flushes within one job run on a small worker pool — the
    ``partition_parallel`` over schedulers of the reference
    (ra_log_segment_writer.erl:129-147): per-uid flushes touch disjoint
    DurableLogs and segment files, so at the co-hosted-thousands design
    point one Python thread would serialize the node's entire flush
    bandwidth.  Jobs themselves stay ordered (two jobs may carry the
    same uid); the WAL-file deletion barrier is preserved — a file is
    unlinked only after every uid's flush in its job completed."""

    #: per-uid flush attempts before escalation (first try + retries)
    FLUSH_ATTEMPTS = 3
    #: base backoff between flush retries (doubles per attempt)
    FLUSH_BACKOFF_S = 0.05

    def __init__(self, resolve: Optional[Callable] = None,
                 flush_workers: int = 4,
                 on_escalate: Optional[Callable] = None) -> None:
        #: resolve(uid) -> DurableLog | None (set by the node/log registry)
        self.resolve = resolve or (lambda uid: None)
        #: escalation hook: called as on_escalate(uid, exc) when a uid's
        #: flush exhausted its retry budget — the "server exit +
        #: supervisor restart" rung of the degradation ladder (the WAL
        #: file is kept either way, so the entries stay recoverable)
        self.on_escalate = on_escalate
        #: node-wide counters (ra_log_segment_writer.erl:37-52 names)
        from ..metrics import SEGMENT_WRITER_FIELDS
        self.counters: dict[str, int] = {f: 0
                                         for f in SEGMENT_WRITER_FIELDS}
        # force-deleted uids: an unresolvable uid in this set means "skip
        # its entries", not "keep the WAL file for a future restart"
        self._deleted: set = set()
        self._queue: "queue.Queue" = queue.Queue()
        self._stop = False
        # daemon worker pool (NOT concurrent.futures: its atexit hook
        # joins workers, so a flush stuck in fsync on a dying disk would
        # hang process exit — the writer thread itself is daemon for the
        # same reason)
        self._pool = _DaemonPool(max(1, flush_workers),
                                 "ra-segment-flush")
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ra-segment-writer")
        self._thread.start()

    def mark_deleted(self, uid: str) -> None:
        """Called on force-delete so flush jobs already queued (or queued
        later) for this uid do not pin their WAL files forever."""
        self._deleted.add(uid)

    def accept_ranges(self, ranges: dict, wal_path: str) -> None:
        """Called by the WAL on rollover (accept_mem_tables/3)."""
        self._queue.put(("__job__", ranges, wal_path))

    def retire(self, uids: list, wal_files: list) -> None:
        """Flush each uid's memtable up to its confirmed tail, then delete
        the recovered WAL files they came from."""
        self._queue.put(("__retire__", uids, wal_files))

    def await_idle(self, timeout: float = 10.0) -> None:
        """Barrier used by tests and log init (await/1 :87-100)."""
        done = threading.Event()
        self._queue.put(("__barrier__", done))
        if not done.wait(timeout):
            raise TimeoutError("segment writer barrier timed out")

    def _run(self) -> None:
        while not self._stop:
            try:
                job = self._queue.get(timeout=0.05)
            except queue.Empty:
                continue
            if job[0] == "__barrier__":
                job[1].set()
                continue
            try:
                if job[0] == "__retire__":
                    self._retire_job(job[1], job[2])
                elif job[0] == "__retire_retry__":
                    self._retire_job(job[1], job[2], job[3])
                else:
                    self._flush_job(job[1], job[2])
            except Exception:  # pragma: no cover
                import logging
                logging.getLogger("ra_tpu").exception(
                    "segment writer job failed: %r", job[:1])

    def _flush_job(self, ranges: dict, wal_path: str) -> None:
        unresolved = False
        jobs = []
        for uid, (lo, hi) in ranges.items():
            log = self.resolve(uid)
            if log is None:
                # a STOPPED server's entries live only in this WAL file:
                # keep it so restart recovery can replay them.  A DELETED
                # server's entries are garbage — they must not pin the
                # file (purge may race a job already queued at rollover)
                if uid not in self._deleted:
                    unresolved = True
                continue
            jobs.append((uid, log, hi))
        # fan the per-uid flushes over the pool (partition_parallel role)
        futures = [(uid, log, hi,
                    self._pool.submit(log.flush_mem_to_segments, hi))
                   for uid, log, hi in jobs]
        for uid, log, hi, fut in futures:
            try:
                self._count_flush(fut.result())
            except Exception as exc:
                if not self._retry_flush(uid, log, hi, exc):
                    unresolved = True  # keep WAL file: still recoverable
        if not unresolved:
            # all servers flushed: the WAL file is redundant (:206-214)
            try:
                os.unlink(wal_path)
            except FileNotFoundError:
                pass

    def _retry_flush(self, uid: str, log, hi, exc: Exception) -> bool:
        """Retry-with-backoff rung of the flush degradation ladder
        (retry -> escalate).  flush() leaves its bookkeeping
        retry-shaped (same pwrites, re-dirtied pages), so re-running the
        whole memtable drain is idempotent.  Returns True when a retry
        succeeded; on exhaustion fires the escalation hook and returns
        False — the caller keeps the WAL file, so the entries remain
        recoverable from disk whatever the escalation does."""
        import logging
        log_ = logging.getLogger("ra_tpu")
        log_.warning("segment flush failed for %s (%s); retrying",
                     uid, exc)
        _fault_note("faults_hit")
        for attempt in range(1, self.FLUSH_ATTEMPTS):
            time.sleep(self.FLUSH_BACKOFF_S * (2 ** (attempt - 1)))
            _fault_note("flush_retries")
            try:
                self._count_flush(log.flush_mem_to_segments(hi))
                return True
            except Exception as retry_exc:  # noqa: BLE001 — ladder rung
                exc = retry_exc
        _fault_note("flush_escalations")
        log_.error("segment flush for %s exhausted %d attempts (%s); "
                   "escalating", uid, self.FLUSH_ATTEMPTS, exc)
        if self.on_escalate is not None:
            try:
                self.on_escalate(uid, exc)
            except Exception:  # noqa: BLE001 — hook must not kill writer
                log_.exception("flush escalation hook failed for %s", uid)
        return False

    def _retire_job(self, uids: list, wal_files: list,
                    attempt: int = 0) -> None:
        for uid in uids:
            log = self.resolve(uid)
            if log is None:
                # registration raced the registry insert: retry briefly,
                # then keep the files (recovery will re-read them — safe)
                if attempt < 20:
                    t = threading.Timer(
                        0.05, lambda: self._queue.put(
                            ("__retire_retry__", uids, wal_files,
                             attempt + 1)))
                    t.daemon = True
                    t.start()
                return
        futures = []
        for uid in uids:
            log = self.resolve(uid)
            if log is not None:
                futures.append((uid, log, self._pool.submit(
                    lambda lg=log: lg.flush_mem_to_segments(
                        lg.last_written().index))))
        failed = False
        for uid, log, fut in futures:
            try:
                self._count_flush(fut.result())
            except Exception as exc:  # noqa: BLE001 — enters retry ladder
                if not self._retry_flush(uid, log,
                                         log.last_written().index, exc):
                    failed = True
        if failed:
            return  # keep the recovered files: entries still needed
        for path in wal_files:
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass

    def _count_flush(self, stats: Optional[tuple]) -> None:
        # counting stays on the single writer thread (futures are
        # resolved there), so no lock is needed
        if not stats:
            return
        entries, nbytes, segs = stats
        self.counters["mem_tables"] += 1
        self.counters["entries"] += entries
        self.counters["bytes_written"] += nbytes
        self.counters["segments"] += segs

    def close(self) -> None:
        self._stop = True
        self._thread.join(timeout=5)
        self._pool.stop()
