"""Fan-in write-ahead log — one writer for every server on the node.

Mirrors the layering of the reference WAL (ra_log_wal.erl):
* single fan-in writer batching the writes of ALL co-hosted servers,
  amortizing one durability syscall across the batch (:193-214, :753-800)
* per-record framing with writer id, idx/term, payload crc (:404-453)
* out-of-sequence writer detection -> resend_from signal (:457-481)
* rollover at max size: the closed file's per-writer ranges go to the
  segment writer, which flushes each server's memtable to its segment
  files and then deletes the WAL file (:593-620, 690-739,
  ra_log_segment_writer.erl:129-201)
* recovery re-reads surviving *.wal files in order into per-uid tables,
  deduping overwrites; DurableLog init consumes them (:334-390, :871-955)

Division of labour (simplified vs the reference, same guarantees): the
*DurableLog* owns the per-server memtable (the reference keeps it in
WAL-owned ETS so it survives WAL crashes; here both live in one process,
so one copy suffices).  The WAL is purely the durability+ordering fan-in:
entries stay in the owner's memtable until a segment flush confirm prunes
them, and the closed WAL file is only deleted after that flush — so every
entry is always recoverable from exactly one of {wal files, segments}.

Hot path (encode+write+sync) goes through ra_tpu.native with the GIL
released.

File format "RTW3": magic(4B) then records:
  type:u8
    1 = writer registration: wid:u32 uid_len:u16 uid
    2 = entry: wid:u32 idx:u64 term:u64 len:u32 crc:u32 payload
    3 = batch run (ISSUE 18): wid:u32 count:u32 body_len:u32 crc:u32
        body = count x (idx:u64 term:u64 slot:u32) triplets
        (body_len == count*20 exactly).  ``slot`` indexes the file's
        CUMULATIVE payload table (type 4): payload images are interned
        once per file, so the three co-hosted members of a cluster
        writing the same entry burst into the shared WAL cost one
        payload image plus three 20-byte triplet runs — the payload
        fan-out was the dominant share of WAL bytes (and of the crc +
        write(2) time under them) once group commit amortized the
        fsync (ISSUE 13 -> 18).  One writer's contiguous burst is ONE
        record with ONE streaming crc over header+body.
    4 = payload-table append (ISSUE 18): n:u32 body_len:u32 crc:u32
        body = n x len:u32, then the n payload images concatenated.
        Appends n images to the file-scope payload table consumed by
        every later type-3 record; the writer emits one per batch run
        that carries images not already interned in this file.
        Payloads are ra_tpu.codec images, relayed byte-for-byte from
        whoever encoded them first.
RTW2 (same layout, no types 3/4, per-entry header crc) and RTW1
(payload-only entry crc) files remain readable — the format version
rides the file magic, so pre-codec data dirs recover unchanged.
"""
from __future__ import annotations

import collections
import os
import queue
import struct
import threading
import time
from typing import Callable, Optional

from .. import trace
from ..blackbox import RECORDER, record
from .faults import IO, note as _fault_note

MAGIC = b"RTW3"
MAGIC_V2 = b"RTW2"   # no batch-run records (read-compatible)
MAGIC_V1 = b"RTW1"   # payload-only entry crc (read-compatible)
_REG = struct.Struct("<BIH")        # type, wid, uid_len
_ENT = struct.Struct("<BIQQII")     # type, wid, idx, term, len, crc
_ENT_HDR = struct.Struct("<BIQQI")  # the crc-covered prefix of _ENT
_RUN_HDR = struct.Struct("<BIII")   # type, wid, count, body_len
_RUN_ENT = struct.Struct("<QQI")    # idx, term, slot (run-table triplet)
_PAY_HDR = struct.Struct("<BII")    # type, n, body_len (payload table)
_CRC = struct.Struct("<I")


def _entry_crc(header: bytes, payload: bytes) -> int:
    """RTW2 record crc covers the HEADER FIELDS as well as the payload:
    a flipped wid/idx/term must fail the check and stop recovery at the
    damage point, not silently skip or mis-file the entry (the tail
    discipline of ra_log_wal.erl:871-955).  RTW1 files (payload-only
    crc) remain readable — the format version rides the file magic.
    One streaming-equivalent crc call (crc32(h+p) == crc32(p, crc32(h)))
    — the two-call form paid a second shim+FFI round trip per record
    on the batch thread's hot loop (ISSUE 13)."""
    return IO.crc32(header + payload)

#: ra.hrl:191's wal_max_size_bytes.  Matching the reference matters
#: beyond parity: rollover triggers the segment flush, and a larger
#: file lets release cursors truncate most of the memtable BEFORE the
#: flush sees it — at 64MB the classic bench segment-flushed ~1.3
#: entries per applied command, at 256MB ~0.2 (ISSUE 18)
DEFAULT_MAX_SIZE = 256 * 1024 * 1024
DEFAULT_MAX_BATCH = 8192              # ra.hrl:192

#: consecutive faulted batches before the poison/rollover ladder gives
#: up and escalates to thread death (supervisor restart + intensity
#: window) — a persistent fault (dead disk, full volume) must not
#: hot-loop file rollovers
MAX_POISON_STREAK = 3

#: notify(uid, lo, hi, term) — lo None => resend_from(hi)
NotifyFn = Callable[[str, Optional[int], int, int], None]


class WalDown(RuntimeError):
    """The WAL batch thread is dead: writes cannot become durable.  The
    reference surfaces the same condition as the ``wal_down`` error a
    server gets calling a crashed ra_log_wal process
    (ra_server.erl:538-554); cores react by entering await_condition
    until the supervisor restarts the WAL."""


def _parse_wal_bytes(data: bytes) -> tuple:
    """Parse raw WAL bytes -> (records, err): the prefix of records up
    to the first damage point, and the ValueError describing it (None
    when the file parses clean).  Records are ("reg", wid, uid) and
    ("ent", wid, idx, term, payload) — pure parsing, no table mutation,
    so a corrupt read can be retried without double-applying."""
    records: list = []
    if data[:4] not in (MAGIC, MAGIC_V2, MAGIC_V1):
        return records, None
    header_crc = data[:4] != MAGIC_V1
    payloads: list = []   # file-scope table type-4 appends / type-3 reads
    pos = 4
    while pos + 1 <= len(data):
        rtype = data[pos]
        if rtype == 1:
            if pos + _REG.size > len(data):
                return records, ValueError("torn registration")
            _, wid, ulen = _REG.unpack_from(data, pos)
            pos += _REG.size
            try:
                uid = data[pos:pos + ulen].decode()
            except UnicodeDecodeError:
                return records, ValueError("corrupt registration uid")
            pos += ulen
            records.append(("reg", wid, uid))
        elif rtype == 2:
            if pos + _ENT.size > len(data):
                return records, ValueError("torn entry header")
            _, wid, idx, term, plen, crc = _ENT.unpack_from(data, pos)
            pos += _ENT.size
            payload = data[pos:pos + plen]
            pos += plen
            want = _entry_crc(_ENT_HDR.pack(2, wid, idx, term, plen),
                              payload) if header_crc else IO.crc32(payload)
            if len(payload) < plen or want != crc:
                return records, ValueError("crc mismatch")  # torn tail
            records.append(("ent", wid, idx, term, payload))
        elif rtype == 3:
            # batch run: validate the WHOLE run (one streaming crc, then
            # the triplet table against body_len) before appending any
            # of its entries — a run lands atomically or not at all,
            # which is exactly the confirm contract (nothing in a batch
            # is confirmed before its full write + sync)
            if pos + _RUN_HDR.size + _CRC.size > len(data):
                return records, ValueError("torn run header")
            _, wid, count, body_len = _RUN_HDR.unpack_from(data, pos)
            (crc,) = _CRC.unpack_from(data, pos + _RUN_HDR.size)
            body_start = pos + _RUN_HDR.size + _CRC.size
            body = data[body_start:body_start + body_len]
            if len(body) < body_len or IO.crc32(
                    body, IO.crc32(data[pos:pos + _RUN_HDR.size])) != crc:
                return records, ValueError("crc mismatch")  # torn tail
            if body_len != count * _RUN_ENT.size:
                return records, ValueError("run table size mismatch")
            navail = len(payloads)
            for i in range(count):
                idx, term, slot = _RUN_ENT.unpack_from(
                    body, i * _RUN_ENT.size)
                if slot >= navail:
                    return records, ValueError("run slot out of range")
                records.append(("ent", wid, idx, term, payloads[slot]))
            pos = body_start + body_len
        elif rtype == 4:
            # payload-table append: crc-validate the whole record, then
            # extend the file-scope table — later type-3 runs reference
            # these images by slot
            if pos + _PAY_HDR.size + _CRC.size > len(data):
                return records, ValueError("torn payload-table header")
            _, n, body_len = _PAY_HDR.unpack_from(data, pos)
            (crc,) = _CRC.unpack_from(data, pos + _PAY_HDR.size)
            body_start = pos + _PAY_HDR.size + _CRC.size
            body = data[body_start:body_start + body_len]
            if len(body) < body_len or IO.crc32(
                    body, IO.crc32(data[pos:pos + _PAY_HDR.size])) != crc:
                return records, ValueError("crc mismatch")  # torn tail
            lens_len = n * 4
            if lens_len > body_len:
                return records, ValueError("payload lens overrun body")
            lens = struct.unpack_from("<%dI" % n, body)
            if lens_len + sum(lens) != body_len:
                return records, ValueError("payload blobs overrun body")
            off = lens_len
            for ln in lens:
                payloads.append(body[off:off + ln])
                off += ln
            pos = body_start + body_len
        else:
            break
    return records, None


def scan_wal_file(path: str, tables: dict) -> None:
    """Parse one WAL file into per-uid tables (idx -> (term, payload)),
    deduping overwrites; raises on a torn/corrupt tail (callers keep the
    prefix parsed so far).  A parse failure is retried ONCE with a fresh
    read — the crc caught the damage either way (counted as a
    crc_catch), but transient read-side corruption (a flipped bit in
    flight, not on the platter) must not truncate recovery when a
    second read comes back clean.  Shared by live recovery and offline
    replay (ra_dbg)."""
    records, err = _parse_wal_bytes(IO.read_file(path))
    if err is not None:
        retry, retry_err = _parse_wal_bytes(IO.read_file(path))
        if retry_err is None or len(retry) > len(records):
            # the fresh read parsed further: the damage was transient
            # read-side corruption (a bit flipped in flight), not a
            # torn tail on the platter — only THIS case is a crc catch;
            # an identical re-parse is an ordinary torn tail (every
            # kill-9 recovery) and is not fault telemetry
            _fault_note("crc_catches")
            records, err = retry, retry_err
    wid_to_uid: dict[int, str] = {}
    for rec in records:
        if rec[0] == "reg":
            wid_to_uid[rec[1]] = rec[2]
            continue
        _kind, wid, idx, term, payload = rec
        uid = wid_to_uid.get(wid)
        if uid is None:
            continue
        tbl = tables.setdefault(uid, {})
        if idx in tbl or any(k > idx for k in tbl):
            # overwrite invalidates higher indexes (dedup,
            # ra_log_wal recovery semantics :871-955)
            for k in [k for k in tbl if k > idx]:
                del tbl[k]
        tbl[idx] = (term, payload)
    if err is not None:
        raise err


class _Writer:
    __slots__ = ("uid", "wid", "notify", "last_idx")

    def __init__(self, uid: str, wid: int, notify: NotifyFn) -> None:
        self.uid = uid
        self.wid = wid
        self.notify = notify
        self.last_idx: Optional[int] = None


class Wal:
    """Node-wide fan-in WAL with a background batch thread."""

    def __init__(self, data_dir: str, *, sync_mode: int = 1,
                 write_strategy: str = "default",
                 max_size: int = DEFAULT_MAX_SIZE,
                 max_batch: int = DEFAULT_MAX_BATCH,
                 max_entries: int = 0,
                 max_batch_bytes: int = 0,
                 max_batch_interval_ms: float = 0.0,
                 segment_writer=None,
                 blackbox_dir: Optional[str] = None,
                 phase_stats=None) -> None:
        """write_strategy (ra_log_wal.erl:66-96):

        * ``default`` — one write(2) for the batch, then the sync_mode
          syscall, then notify (durability before confirmation)
        * ``o_sync`` — the file is opened O_SYNC so the write itself is
          durable; no separate sync syscall (trades batch-write speed
          for no sync latency)
        * ``sync_after_notify`` — write, notify, THEN sync: lowest
          confirm latency, with the documented weaker window (a crash
          between notify and sync can lose confirmed-but-unsynced
          entries of that batch — same contract as the reference)

        Group-commit policy: a batch closes when the mailbox drains
        (today's behavior), when its payload bytes reach
        ``max_batch_bytes``, or when ``max_batch_interval_ms`` has
        elapsed since the group opened — whichever comes first.  With
        the interval at 0 (default) the writer never waits for more
        traffic; a nonzero interval lets bursty writers amortize one
        fdatasync over the whole burst (the fan-in batching axis of
        ra_log_wal.erl:193-214, extended with an explicit wait budget).
        A flush barrier or rollover marker closes the group immediately
        — flush latency never pays the wait budget.
        """
        if write_strategy not in ("default", "o_sync",
                                  "sync_after_notify"):
            raise ValueError(f"unknown write_strategy {write_strategy!r}")
        self.dir = os.path.join(data_dir, "wal")
        os.makedirs(self.dir, exist_ok=True)
        #: where post-mortem bundles land (<dir>/blackbox): a sharded
        #: plane points every shard at ONE home so an incident's
        #: bundles sit together, not one per shard subdir
        self._bb_dir = blackbox_dir or data_dir
        self.sync_mode = sync_mode
        self.write_strategy = write_strategy
        self.max_size = max_size
        #: optional telemetry.PhaseStats — the engine durability bridge
        #: passes its accumulator so the WAL's fsync_wait and
        #: confirm_publish edges join the phase attribution (ISSUE 9);
        #: None (the classic plane default) costs nothing
        self._phases = phase_stats
        self.max_batch_bytes = max_batch_bytes
        self.max_batch_interval_ms = max_batch_interval_ms
        #: bounded reservoir of recent durability-syscall latencies (s)
        self._sync_lats: collections.deque = collections.deque(maxlen=512)
        #: optional per-file record cap (wal_max_entries; the reference
        #: rolls on either limit, ra_log_wal.erl:593-620) — 0 disables
        self.max_entries = max_entries
        self._file_entries = 0
        self.max_batch = max_batch
        self.segment_writer = segment_writer
        self._writers: dict[str, _Writer] = {}
        self._wid_seq = 0
        self._lock = threading.Lock()
        self._queue: "queue.Queue" = queue.Queue()
        self._fd: Optional[int] = None
        self._file_seq = 0
        self._file_size = 0
        self._file_path = ""
        self._file_ranges: dict[str, list] = {}  # uid -> [lo, hi] this file
        self._registered_in_file: set = set()
        self._stop = False
        #: consecutive batches that hit an I/O fault (reset on the first
        #: clean batch) — drives the poison -> rollover -> escalate ladder
        self._poison_streak = 0
        #: bumped by restart(); lets observers detect "new WAL incarnation"
        #: (the reference's new-wal-pid check, ra_log.erl:778-793)
        self.generation = 0
        #: node-wide WAL counters (ra_log_wal.erl:32-43 field names)
        from ..metrics import WAL_FIELDS
        self.counters: dict[str, int] = {f: 0 for f in WAL_FIELDS}
        self._recovered: dict[str, dict] = {}
        self._recover()
        self._open_new_file()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ra-wal")
        self._thread.start()

    @property
    def alive(self) -> bool:
        return self._thread.is_alive() and not self._stop

    @property
    def phases(self):
        """The phase accumulator this WAL stamps (None when the owner
        didn't wire one) — DurableLog adds its encode stamps to the
        same accumulator so one overview covers the whole plane."""
        return self._phases

    # -- registration -------------------------------------------------------

    def register(self, uid: str, notify: NotifyFn) -> None:
        retire = None
        with self._lock:
            w = self._writers.get(uid)
            if w is None:
                self._wid_seq += 1
                self._writers[uid] = _Writer(uid, self._wid_seq, notify)
            else:
                w.notify = notify
                w.last_idx = None  # restarted writer: fresh sequence check
            # once every uid found in recovered WAL files has re-registered,
            # their entries (now in DurableLog memtables) can be flushed to
            # segments and the old files retired (the reference deletes WAL
            # files once their tables are flushed, :206-214)
            if self._recovered_files and \
                    set(self._recovered).issubset(self._writers):
                retire = (list(self._recovered), list(self._recovered_files))
                self._recovered_files = []
        if retire is not None and self.segment_writer is not None:
            uids, files = retire
            self.segment_writer.retire(uids, files)

    def purge(self, uid: str) -> None:
        """Forget a deleted server: drop its writer registration, its
        range in the current file, and its recovered table.  Without this
        a force-deleted uid pins WAL files forever — rollover keeps any
        file whose ranges contain an unresolvable uid, and the recovery
        retirement gate (register) waits for a registration that will
        never come.  Its already-written bytes in shared files remain
        until those files rotate out, as in the reference's shared WAL."""
        retire = None
        with self._lock:
            self._writers.pop(uid, None)
            self._file_ranges.pop(uid, None)
            self._recovered.pop(uid, None)
            if self._recovered_files and \
                    set(self._recovered).issubset(self._writers):
                retire = (list(self._recovered),
                          list(self._recovered_files))
                self._recovered_files = []
        if self.segment_writer is not None:
            # flush jobs already queued for this uid must skip it rather
            # than keep their WAL file waiting for a server that will
            # never come back
            self.segment_writer.mark_deleted(uid)
            if retire is not None:
                self.segment_writer.retire(*retire)

    # -- write path ---------------------------------------------------------

    def write(self, uid: str, index: int, term: int, payload: bytes,
              truncate: bool = False) -> None:
        """Async append; confirmation arrives via notify after the batch
        reaches disk.  truncate marks a post-snapshot-install write
        (wal_truncate_write, ra_log.erl:1033).  Raises WalDown when the
        batch thread is dead (the failed gen-call to a crashed
        ra_log_wal)."""
        if not self.alive:
            raise WalDown("wal batch thread is down")
        self._queue.put((uid, index, term, payload, truncate))

    def write_many(self, uid: str, items: list) -> None:
        """Group-commit fan-in submit (ISSUE 13): hand a CONTIGUOUS
        run of entries for one writer to the batch thread as ONE queue
        item — the per-entry ``write`` path costs one lock/notify
        hand-off per entry, which at batch-append rates dominates the
        submitting (event-loop) thread.  ``items`` is
        ``[(index, term, payload, truncate), ...]`` with ascending
        consecutive indexes; the batch thread applies the same
        gap-check/confirm bookkeeping once per run instead of once per
        entry, and the run lands under the same fsync group as every
        other co-hosted writer's burst."""
        if not items:
            return
        if not self.alive:
            raise WalDown("wal batch thread is down")
        self._queue.put(("__many__", uid, items, b"", None))

    def flush(self, timeout: float = 5.0) -> None:
        """Barrier: wait until everything queued so far is durable."""
        if not self.alive:
            raise WalDown("wal batch thread is down")
        done = threading.Event()
        self._queue.put(("__flush__", 0, 0, b"", done))
        if not done.wait(timeout):
            if not self.alive:
                raise WalDown("wal died during flush")
            raise TimeoutError("wal flush timed out")

    def rollover(self) -> None:
        """Force a rollover (tests + snapshot truncation)."""
        self._queue.put(("__roll__", 0, 0, b"", None))

    # -- batch thread -------------------------------------------------------

    def _run(self) -> None:
        while not self._stop:
            try:
                first = self._queue.get(timeout=0.05)
            except queue.Empty:
                continue
            if first[0] == "__crash__":
                # test hook: die like a real batch-thread crash (no
                # cleanup, fd left open, queued writes abandoned).
                # A kill-9 of the WAL is a flight-recorder trigger:
                # dump the post-mortem bundle before dying (the
                # nemesis wal_kill / soak --blackbox path)
                self._crash_dump()
                raise RuntimeError("wal killed")
            batch = [first]
            # cap the batch at the remaining per-file entry budget so a
            # file never exceeds max_entries (the reference evaluates
            # its roll condition per write, ra_log_wal.erl:426-441 —
            # batch-granularity enforcement alone could overshoot by a
            # whole max_batch under bursty load).  A __many__ fan-in
            # item counts its whole run (it is never split: the run is
            # one writer's contiguous burst) — it may overshoot the cap
            # by at most one run, exactly like the old per-write
            # granularity could overshoot by one write.
            cap = self.max_batch
            if self.max_entries:
                cap = min(cap, max(1, self.max_entries -
                                   self._file_entries))
            # group-commit collection: greedy drain, optionally holding
            # the group open up to max_batch_interval_ms / until
            # max_batch_bytes, so one fdatasync covers the whole burst.
            # Flush/roll markers close the group immediately.
            urgent = first[0] in ("__flush__", "__roll__")
            group_count, group_bytes = (0, 0) if urgent else \
                self._item_weight(first)
            deadline = (time.monotonic() + self.max_batch_interval_ms
                        / 1000.0) if self.max_batch_interval_ms > 0 \
                else None
            while group_count < cap and not urgent:
                if self.max_batch_bytes and \
                        group_bytes >= self.max_batch_bytes:
                    break
                try:
                    if deadline is None:
                        item = self._queue.get_nowait()
                    else:
                        wait = deadline - time.monotonic()
                        item = self._queue.get_nowait() if wait <= 0 \
                            else self._queue.get(timeout=wait)
                except queue.Empty:
                    break
                if item[0] == "__crash__":
                    # the crash hook must fire even when collected into
                    # an open group (interval mode)
                    self._crash_dump()
                    raise RuntimeError("wal killed")
                batch.append(item)
                if item[0] in ("__flush__", "__roll__"):
                    urgent = True
                else:
                    n, b = self._item_weight(item)
                    group_count += n
                    group_bytes += b
            # a hard batch failure (disk error) kills the thread — the
            # supervisor restarts the WAL and writers resend, the same
            # let-it-crash shape as the reference's ra_log_wal under
            # ra_log_wal_sup (ra_log_sup.erl:26-51)
            with trace.span("wal.batch", "wal", n=len(batch)):
                self._write_batch(batch)

    @staticmethod
    def _item_weight(item) -> tuple:
        """(entry count, payload bytes) of one queue item — a plain
        write weighs 1, a __many__ fan-in run weighs its whole batch."""
        if item[0] == "__many__":
            return len(item[2]), sum(len(p) for _i, _t, p, _tr in item[2])
        return 1, len(item[3])

    def kill(self) -> None:
        """Simulate a WAL crash (tests / fault injection)."""
        self._queue.put(("__crash__", 0, 0, b"", None))
        self._thread.join(timeout=5)

    def _crash_dump(self) -> None:
        """Flight-recorder trigger for an injected WAL kill: record the
        event and write the post-mortem bundle next to the data dir."""
        record("wal.kill", file=self._file_path,
               queue_depth=self._queue.qsize())
        RECORDER.dump("wal_kill", what="injected WAL batch-thread kill",
                      where=self._file_path, data_dir=self._bb_dir)

    def restart(self) -> None:
        """Supervisor hook: revive a crashed WAL.

        The half-written current file keeps everything that was confirmed
        (notify only follows durability), so its per-writer ranges are
        handed to the segment writer exactly like a rollover.  Queued but
        unwritten entries are dropped — they were never confirmed, and
        writers resend everything above last_written after a restart
        (DurableLog.wal_restarted, mirroring ra_log.erl:778-793)."""
        if self.alive or self._stop:
            return
        with self._lock:
            self._queue = queue.Queue()  # crash loses the mailbox
            for w in self._writers.values():
                w.last_idx = None  # writers resend; fresh sequence check
        self._retire_current_file()
        self._poison_streak = 0  # fresh incarnation, fresh ladder
        self.generation += 1
        record("wal.restart", generation=self.generation)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ra-wal")
        self._thread.start()

    def _write_batch(self, batch: list) -> None:
        buf = bytearray()
        flushes = []
        roll = False
        confirms: dict[str, list] = {}  # uid -> [lo, hi, term]
        pending_last: dict[str, int] = {}  # provisional last_idx this batch
        new_regs: set = set()
        n_entries = 0
        pack_hdr = _ENT_HDR.pack
        pack_crc = _CRC.pack
        with self._lock:
            for item in batch:
                uid = item[0]
                if uid == "__flush__":
                    flushes.append(item[4])
                    continue
                if uid == "__roll__":
                    roll = True
                    continue
                if uid == "__many__":
                    # fan-in run: one writer's contiguous batch — the
                    # gap check, registration, and confirm-range update
                    # happen ONCE per run; only pack/crc/append remain
                    # per entry (the irreducible record-format work)
                    _tag, muid, items = item[0], item[1], item[2]
                    w = self._writers.get(muid)
                    if w is None:
                        continue
                    first_idx = items[0][0]
                    last = pending_last.get(muid, w.last_idx)
                    if last is not None and first_idx > last + 1 and \
                            not items[0][3]:
                        record("wal.resend", uid=muid, frm=last,
                               gap_at=first_idx)
                        w.notify(muid, None, last, -1)
                        continue
                    if w.wid not in self._registered_in_file and \
                            w.wid not in new_regs:
                        ub = w.uid.encode()
                        buf += _REG.pack(1, w.wid, len(ub))
                        buf += ub
                        new_regs.add(w.wid)
                    # the run lands as ONE type-3 record: a bulk-packed
                    # triplet table, one streaming crc — no per-entry
                    # pack/crc/append on the batch thread.  Payload
                    # images intern into the file-scope table (type 4):
                    # co-hosted members writing the same replicated
                    # burst pay the image bytes once, not once per
                    # member — the fan-out was most of the WAL's crc +
                    # write(2) volume
                    intern = self._intern
                    nslot = self._intern_n
                    new_lens: list = []
                    new_blobs: list = []
                    flat: list = []
                    grow = flat.append
                    for index, term, payload, _trunc in items:
                        slot = intern.get(payload)
                        if slot is None:
                            slot = intern[payload] = nslot
                            nslot += 1
                            new_lens.append(len(payload))
                            new_blobs.append(payload)
                        grow(index)
                        grow(term)
                        grow(slot)
                    if new_blobs:
                        lens = struct.pack("<%dI" % len(new_lens),
                                           *new_lens)
                        cat = b"".join(new_blobs)
                        phdr = _PAY_HDR.pack(4, len(new_blobs),
                                             len(lens) + len(cat))
                        pcrc = IO.crc32(cat, IO.crc32(lens,
                                                      IO.crc32(phdr)))
                        buf += phdr
                        buf += pack_crc(pcrc)
                        buf += lens
                        buf += cat
                        self._intern_n = nslot
                    tab = struct.pack("<" + "QQI" * len(items), *flat)
                    hdr = _RUN_HDR.pack(3, w.wid, len(items), len(tab))
                    crc = IO.crc32(tab, IO.crc32(hdr))
                    buf += hdr
                    buf += pack_crc(crc)
                    buf += tab
                    n_entries += len(items)
                    last_item = items[-1]
                    pending_last[muid] = last_item[0]
                    c = confirms.setdefault(
                        muid, [first_idx, last_item[0], last_item[1]])
                    c[0] = min(c[0], first_idx)
                    c[1] = max(c[1], last_item[0])
                    c[2] = last_item[1]
                    continue
                _uid, index, term, payload, extra = item
                w = self._writers.get(uid)
                if w is None:
                    continue
                truncate = bool(extra)
                last = pending_last.get(uid, w.last_idx)
                if last is not None and index > last + 1 and not truncate:
                    # gap: out-of-sequence write — tell the writer to
                    # resend from its last accepted index (:457-481)
                    record("wal.resend", uid=uid, frm=last, gap_at=index)
                    w.notify(uid, None, last, -1)
                    continue
                if w.wid not in self._registered_in_file and \
                        w.wid not in new_regs:
                    ub = w.uid.encode()
                    buf += _REG.pack(1, w.wid, len(ub))
                    buf += ub
                    new_regs.add(w.wid)
                hdr = pack_hdr(2, w.wid, index, term, len(payload))
                buf += hdr
                buf += pack_crc(_entry_crc(hdr, payload))
                buf += payload
                n_entries += 1
                pending_last[uid] = index
                c = confirms.setdefault(uid, [index, index, term])
                c[0] = min(c[0], index)
                c[1] = max(c[1], index)
                c[2] = term
        deferred_sync = False
        if buf:
            # IO first, bookkeeping after: if the write throws, last_idx
            # and _file_ranges still describe only bytes the file really
            # holds — rollover/restart hand _file_ranges to the segment
            # writer, which flushes and then DELETES the file, so
            # overstating the ranges would silently drop acknowledged
            # entries
            try:
                if self.write_strategy == "o_sync":
                    # O_SYNC fd: the write IS the durability point
                    n = IO.write_batch(self._fd, bytes(buf), 0)
                elif self.write_strategy == "sync_after_notify":
                    n = IO.write_batch(self._fd, bytes(buf), 0)
                    deferred_sync = self.sync_mode != 0
                else:
                    n = IO.write_batch(self._fd, bytes(buf), 0)
                    if self.sync_mode:
                        self._timed_sync()
            except OSError as exc:
                # nothing was confirmed: bookkeeping and notify are
                # skipped, the batch's entries stay memtable-resident,
                # and the degradation ladder (poison -> rollover ->
                # resend, escalate after a streak) takes over
                self._on_batch_io_error(exc, flushes)
                return
            self._poison_streak = 0
            self._file_size += n
            self._file_entries += n_entries
            self.counters["batches"] += 1
            self.counters["writes"] += n_entries
            self.counters["bytes_written"] += n
            # flight-recorder hop: the batch's per-uid index ranges are
            # the (uid, idx) join key ra_trace resolves traced commands'
            # WAL-write time through
            record("wal.write", file=os.path.basename(self._file_path),
                   n=n_entries, bytes=n,
                   ranges={u: [c[0], c[1]] for u, c in confirms.items()})
            with self._lock:
                self._registered_in_file |= new_regs
                for uid, last in pending_last.items():
                    w = self._writers.get(uid)
                    if w is None:
                        continue  # purged mid-write: no range resurrection
                    w.last_idx = last
                    lo = confirms[uid][0]
                    r = self._file_ranges.setdefault(uid, [lo, last])
                    r[0] = min(r[0], lo)
                    r[1] = max(r[1], last)
        # notify AFTER durability (complete_batch, :753-800)
        with self._lock:
            notifiers = [(self._writers[uid].notify, uid, c)
                         for uid, c in confirms.items()
                         if uid in self._writers]
        t_pub = time.monotonic() if notifiers else 0.0
        for notify, uid, (lo, hi, term) in notifiers:
            record("wal.confirm", uid=uid, lo=lo, hi=hi)
            notify(uid, lo, hi, term)
        if notifiers and self._phases is not None:
            # confirm_publish phase stamp: durability -> every writer's
            # confirm callback returned (the fan-out the commit quorum
            # waits behind)
            self._phases.note("confirm_publish",
                              time.monotonic() - t_pub)
        if deferred_sync:
            # sync_after_notify: durability syscall AFTER the confirms
            # (complete_batch with post-notify sync, ra_log_wal.erl:66-96)
            try:
                self._timed_sync()
            except OSError as exc:
                # the documented weaker window of this strategy: the
                # batch was already confirmed but may not be durable.
                # Poison + rollover; passing the batch's confirm window
                # makes the resend reach BELOW last_idx and re-write the
                # confirmed-but-unsynced suffix into the fresh file,
                # closing the window going forward.
                self._on_batch_io_error(exc, flushes, confirmed=confirms)
                return
        if roll or self._file_size >= self.max_size or \
                (self.max_entries and
                 self._file_entries >= self.max_entries):
            self._rollover()
        # flush barriers release only after any requested rollover has been
        # handed to the segment writer (callers chain await_idle after)
        for done in flushes:
            done.set()

    def _on_batch_io_error(self, exc: OSError, flushes: list,
                           confirmed: Optional[dict] = None) -> None:
        """Degradation policy for a failed batch write or durability
        syscall — the fsyncgate discipline made supervision-shaped:

        * the current file is POISONED: its fd is never fsynced again
          (after a failed fsync the kernel may have dropped the dirty
          pages, so a retried fsync can report success over lost data).
          The file is retired exactly like a rollover — its confirmed
          ranges go to the segment writer, which flushes them from the
          MEMTABLES, so nothing acknowledged depends on the bad file.
        * every registered writer gets a resend_from signal at its last
          accepted index: unconfirmed entries re-enter the queue and
          land in the fresh file (writers re-register on first write).
        * flush barriers are RE-QUEUED, not released — a durability
          barrier may only trip once the resends are really on disk.
        * MAX_POISON_STREAK consecutive faulted batches escalate to
          thread death: the supervisor restarts the WAL under its
          intensity window instead of this thread hot-looping rollovers
          against a dead disk.
        """
        import logging
        logging.getLogger("ra_tpu").warning(
            "wal batch I/O error (%s): poisoning %s",
            exc, self._file_path)
        _fault_note("faults_hit")
        _fault_note("poisoned_files")
        self._poison_streak += 1
        record("wal.poison", file=os.path.basename(self._file_path),
               error=repr(exc)[:200], streak=self._poison_streak)
        if self._poison_streak >= MAX_POISON_STREAK:
            _fault_note("wal_escalations")
            record("wal.escalate", streak=self._poison_streak,
                   error=repr(exc)[:200])
            # black-box trigger: the ladder is giving up this thread —
            # capture the rings + fault-plan state before dying
            RECORDER.dump("wal_escalation",
                          what=f"poison streak {self._poison_streak} "
                               "-> thread death",
                          where=self._file_path, data_dir=self._bb_dir)
            raise exc
        _fault_note("fault_rollovers")
        self._retire_current_file()
        with self._lock:
            # last_idx None (a writer that never confirmed through this
            # incarnation, e.g. right after a supervised restart) means
            # "resend everything memtable-resident": hi=0 — duplicates
            # are harmless (overwrite dedup + stale-confirm clamping).
            # ``confirmed`` (the sync_after_notify failure path) pulls
            # the resend floor below entries that were confirmed ahead
            # of the durability syscall that then failed; those resends
            # carry term=-2 ("unsynced-confirm rewind") so a writer that
            # floor-clamps its resends to its own confirm watermark
            # (DurableLog does) knows to pull that watermark back first
            # instead of trusting the poisoned file for the suffix.
            resends = []
            for w in self._writers.values():
                last = w.last_idx if w.last_idx is not None else 0
                term = -1
                if confirmed and w.uid in confirmed:
                    last = min(last, confirmed[w.uid][0] - 1)
                    term = -2
                resends.append((w.notify, w.uid, max(0, last), term))
        for notify, uid, last, term in resends:
            notify(uid, None, last, term)
        for done in flushes:
            self._queue.put(("__flush__", 0, 0, b"", done))

    def _timed_sync(self) -> None:
        """Durability syscall with latency accounting (the reference
        exposes the same number as wal_sync_time via seshat)."""
        t0 = time.monotonic()
        IO.sync(self._fd, self.sync_mode)
        dt = time.monotonic() - t0
        self.counters["syncs"] += 1
        self.counters["sync_time_us"] += int(dt * 1e6)
        record("wal.fsync", ms=round(dt * 1000, 3),
               file=os.path.basename(self._file_path))
        if self._phases is not None:
            # fsync_wait phase stamp (the durability-syscall edge of
            # the per-window budget attribution)
            self._phases.note("fsync_wait", dt)
        with self._lock:
            # stats() iterates the reservoir from other threads; an
            # unguarded append would intermittently crash that read
            # with "deque mutated during iteration"
            self._sync_lats.append(dt)

    def stats(self) -> dict:
        """Counters plus derived group-commit health: fsync latency
        p50/p99 (from a bounded reservoir of recent syncs) and mean
        records per fsync — the amortization factor group commit buys."""
        d = dict(self.counters)
        with self._lock:
            lats = sorted(self._sync_lats)
        if lats:
            d["fsync_p50_ms"] = round(1000 * lats[len(lats) // 2], 3)
            d["fsync_p99_ms"] = round(
                1000 * lats[min(len(lats) - 1, int(len(lats) * 0.99))], 3)
        else:
            d["fsync_p50_ms"] = d["fsync_p99_ms"] = -1.0
        # -1 sentinel when no durability syscall ever ran (sync_mode=0,
        # o_sync) — matching the fsync percentile sentinels; the raw
        # write count would read as extreme amortization otherwise
        d["records_per_fsync"] = round(
            d["writes"] / d["syncs"], 2) if d["syncs"] else -1.0
        # live write-queue backlog: the group-commit pipeline depth
        # gauge the Observatory/ra_top surface next to fsync latency —
        # a climbing depth with flat p50 means the writer is starved,
        # a climbing depth with climbing p99 means the disk is
        d["queue_depth"] = self._queue.qsize()
        return d

    # -- files / rollover / recovery ---------------------------------------

    def _open_new_file(self) -> None:
        self.counters["wal_files"] += 1
        self._file_seq += 1
        self._file_path = os.path.join(self.dir,
                                       f"{self._file_seq:08d}.wal")
        self._fd = IO.wal_open(self._file_path, truncate=True,
                               o_sync=self.write_strategy == "o_sync")
        IO.write_batch(self._fd, MAGIC, 0)
        self._file_size = len(MAGIC)
        self._file_entries = 0
        self._registered_in_file = set()
        self._file_ranges = {}
        # payload interning is file-scope: type-3 slots index the table
        # accumulated by THIS file's type-4 records, so the dict resets
        # with the file (also on the fault-rollover path — a poisoned
        # file's slots must not leak into the fresh one)
        self._intern: dict = {}
        self._intern_n = 0

    def _rollover(self) -> None:
        self._retire_current_file()

    def _retire_current_file(self) -> None:
        """Close the current file, open a fresh one, and hand the closed
        file's per-writer ranges to the segment writer (an empty file is
        unlinked).  Shared by rollover and crash restart — both retire
        the file the same way."""
        old_fd, old_path = self._fd, self._file_path
        with self._lock:
            ranges = {uid: tuple(r) for uid, r in self._file_ranges.items()}
        try:
            IO.close(old_fd)
        except OSError:
            # safe to swallow: the fd is retiring and is never read or
            # synced again — its confirmed entries are covered by the
            # memtable + segment-flush barrier, and a poisoned fd may
            # legitimately surface its deferred EIO here
            _fault_note("swallowed_oserrors")
        self._open_new_file()
        if ranges and self.segment_writer is not None:
            self.segment_writer.accept_ranges(ranges, old_path)
        elif not ranges:
            try:
                os.unlink(old_path)
            except OSError:
                # safe to swallow: an empty (magic-only) file that fails
                # to unlink leaks bytes, not data — recovery re-reads it
                # as a no-op
                _fault_note("swallowed_oserrors")

    def _recover(self) -> None:
        files = sorted(f for f in os.listdir(self.dir)
                       if f.endswith(".wal"))
        for fname in files:
            path = os.path.join(self.dir, fname)
            try:
                self._recover_file(path)
            except Exception:
                import logging
                logging.getLogger("ra_tpu").warning(
                    "wal recovery: truncated/corrupt tail in %s", fname)
            seq = int(fname.split(".")[0])
            self._file_seq = max(self._file_seq, seq)
        self._recovered_files = [os.path.join(self.dir, f) for f in files]

    def _recover_file(self, path: str) -> None:
        scan_wal_file(path, self._recovered)

    def recovered_table(self, uid: str) -> dict:
        """Entries for uid recovered from surviving WAL files
        (idx -> (term, payload)); consumed by DurableLog init."""
        return self._recovered.get(uid, {})

    def close(self) -> None:
        self._stop = True
        self._thread.join(timeout=5)
        if self._fd is not None:
            IO.close(self._fd)
            self._fd = None
