"""In-memory log backend.

Implements the per-server log contract the pure core depends on — the same
interface the durable log (ra_tpu.log.durable) provides.  Modeled on the
reference's test double /root/reference/test/ra_log_memory.erl plus the parts
of the real facade contract the core observes (/root/reference/src/ra_log.erl):

* ``append``/``write`` are *asynchronous* with respect to durability: entries
  become readable immediately (memtable semantics) but ``last_written`` only
  advances when the owner processes a :class:`~ra_tpu.core.types.WrittenEvent`
  (delivered via :meth:`take_events`).  The quorum arithmetic counts the
  leader's own ``last_written`` (ra_server.erl:2977-2987), so this async
  protocol is load-bearing even in memory.
* ``write`` at an index ≤ ``last_index`` truncates everything after the batch
  (overwrite semantics, ra_log.erl:315-330).
* meta (current_term / voted_for / last_applied) is stored synchronously,
  standing in for ra_log_meta.
"""
from __future__ import annotations

from typing import Any, Callable, Iterable, Optional

from ..core.types import Entry, IdxTerm, SnapshotMeta, WrittenEvent
from ..metrics import LOG_FIELDS
from .snapshot import DEFAULT_SNAPSHOT_MODULE


class IntegrityError(Exception):
    pass


class MemoryLog:
    #: pluggable state serializer (Machine.snapshot_module override,
    #: ra_machine.erl:435-437); container format is module-agnostic
    #: True when term/voted_for/entries survive a process restart —
    #: gates supervised auto-restart (amnesia double-vote hazard)
    durable = False

    snapshot_module = DEFAULT_SNAPSHOT_MODULE

    def __init__(self, *, auto_written: bool = True,
                 first_index: int = 1) -> None:
        # idx -> Entry
        self._entries: dict[int, Entry] = {}
        self._last_index = first_index - 1
        self._last_term = 0
        self._first_index = first_index
        self._last_written = IdxTerm(first_index - 1, 0)
        self._auto_written = auto_written
        self._pending_events: list[WrittenEvent] = []
        # meta store (ra_log_meta stand-in)
        self._meta: dict[str, Any] = {"current_term": 0, "voted_for": None,
                                      "last_applied": 0}
        # snapshot: (SnapshotMeta, machine_state)
        self._snapshot: Optional[tuple] = None
        self._checkpoints: list[tuple] = []  # [(SnapshotMeta, machine_state)]
        # log-subsystem counters (RA_LOG_COUNTER_FIELDS, ra.hrl:236-268);
        # segment/WAL-specific fields stay 0 for the in-memory backend
        self.counters: dict[str, int] = {f: 0 for f in LOG_FIELDS}

    def log_metrics(self) -> dict:
        """Counter snapshot for key_metrics (ra.erl:1229-1257)."""
        return dict(self.counters)

    def wal_is_up(self) -> bool:
        """In-memory log has no WAL thread to die."""
        return True

    # -- ranges -------------------------------------------------------------

    def last_index_term(self) -> IdxTerm:
        return IdxTerm(self._last_index, self._last_term)

    def last_written(self) -> IdxTerm:
        return self._last_written

    def first_index(self) -> int:
        return self._first_index

    def next_index(self) -> int:
        return self._last_index + 1

    # -- writes -------------------------------------------------------------

    def append(self, entry: Entry) -> None:
        """Leader-path append; index must be exactly next_index
        (ra_log:append/2 errors on integrity violation)."""
        if entry.index != self._last_index + 1:
            raise IntegrityError(
                f"append gap: {entry.index} != {self._last_index + 1}")
        self.counters["write_ops"] += 1
        self._entries[entry.index] = entry
        self._last_index = entry.index
        self._last_term = entry.term
        self._queue_written(entry.index, entry.index, entry.term)

    def append_batch(self, entries: list, payloads=None) -> None:
        """Leader-path batch append (ISSUE 13): contiguous strictly-new
        entries, ONE queued written event for the whole run (the batch
        twin of :meth:`append`; ``payloads`` — pre-encoded durable
        images — is accepted for interface parity and ignored, this
        backend keeps no bytes)."""
        if not entries:
            return
        if entries[0].index != self._last_index + 1:
            raise IntegrityError(
                f"append gap: {entries[0].index} != "
                f"{self._last_index + 1}")
        self.counters["write_ops"] += len(entries)
        for e in entries:
            self._entries[e.index] = e
        last = entries[-1]
        self._last_index = last.index
        self._last_term = last.term
        # one confirm for the run: terms are uniform by construction
        # (a leader appends in its own term), so the range event is
        # exactly what the per-entry events would have coalesced into
        self._queue_written(entries[0].index, last.index, last.term)

    def write(self, entries: list, payloads=None) -> None:
        """Follower-path write; may overwrite.  First index must be within
        [first_index, last_index+1]; everything after the batch is
        truncated.  ``payloads`` (pre-encoded durable images shipped in
        the AER, ISSUE 13) is ignored — this backend keeps no bytes."""
        if not entries:
            return
        first = entries[0].index
        if first > self._last_index + 1:
            raise IntegrityError(
                f"write gap: {first} > {self._last_index + 1}")
        self.counters["write_ops"] += len(entries)
        # an overwrite invalidates previous confirms over the rewritten
        # range: rewind last_written to the real predecessor BEFORE the
        # batch lands, so AER replies stay truthful (DurableLog._put does
        # the same; a stale (index, old-term) confirm here livelocks the
        # leader's stale-suffix repair)
        if self._last_written.index >= first:
            prev = first - 1
            self._last_written = IdxTerm(prev, self.fetch_term(prev) or 0)
        for e in entries:
            self._entries[e.index] = e
        last = entries[-1]
        # truncate any stale tail
        for idx in range(last.index + 1, self._last_index + 1):
            self._entries.pop(idx, None)
        self._last_index = last.index
        self._last_term = last.term
        self._queue_written(first, last.index, last.term)

    def set_last_index(self, idx: int) -> None:
        """Truncate back so last index == idx (ra_log:set_last_index,
        used when a valid leader shows a shorter log, ra_server.erl:1058)."""
        if idx >= self._last_index:
            return
        for i in range(idx + 1, self._last_index + 1):
            self._entries.pop(i, None)
        term = self.fetch_term(idx) or 0
        self._last_index = idx
        self._last_term = term
        if self._last_written.index > idx:
            self._last_written = IdxTerm(idx, term)

    def _queue_written(self, from_idx: int, to_idx: int, term: int) -> None:
        if self._auto_written:
            self._pending_events.append(WrittenEvent(from_idx, to_idx, term))

    # -- async written-event protocol --------------------------------------

    def take_events(self) -> list:
        evts, self._pending_events = self._pending_events, []
        return evts

    def release_written(self, from_idx: int, to_idx: int, term: int) -> None:
        """Manual mode: tests script the WAL confirm."""
        self._pending_events.append(WrittenEvent(from_idx, to_idx, term))

    def handle_written(self, evt: WrittenEvent) -> None:
        """Owner processed a written event: advance last_written if the
        entries still match (term check guards against overwrites,
        ra_log.erl:474-529)."""
        term = self.fetch_term(evt.to_index)
        if term == evt.term:
            if evt.to_index > self._last_written.index:
                self._last_written = IdxTerm(evt.to_index, evt.term)
        elif term is None and self._snapshot is not None and \
                self._snapshot[0].index >= evt.to_index:
            # entries already truncated by a snapshot: written info subsumed
            pass
        # else: stale write for an overwritten term — ignore (the real log
        # triggers resend_from; the memory log has nothing to resend)

    def reset_to_last_known_written(self) -> None:
        lw = self._last_written
        self.set_last_index(lw.index)

    # -- reads --------------------------------------------------------------

    def fetch(self, idx: int) -> Optional[Entry]:
        self.counters["read_ops"] += 1
        e = self._entries.get(idx)
        if e is not None:
            self.counters["read_cache"] += 1
        return e

    def fetch_term(self, idx: int) -> Optional[int]:
        self.counters["fetch_term"] += 1
        if self._snapshot is not None and idx == self._snapshot[0].index:
            return self._snapshot[0].term
        e = self._entries.get(idx)
        return e.term if e is not None else None

    def exists(self, idx: int, term: int) -> bool:
        return self.fetch_term(idx) == term

    def fold(self, from_idx: int, to_idx: int,
             fn: Callable[[Entry, Any], Any], acc: Any) -> Any:
        for i in range(from_idx, to_idx + 1):
            e = self._entries.get(i)
            if e is None:
                continue
            acc = fn(e, acc)
        return acc

    def read_range(self, from_idx: int, to_idx: int) -> list:
        return [self._entries[i]
                for i in range(from_idx, to_idx + 1) if i in self._entries]

    def sparse_read(self, indexes: Iterable[int]) -> list:
        return [self._entries[i] for i in indexes if i in self._entries]

    # -- meta ---------------------------------------------------------------

    def store_meta(self, sync: bool = True, **kv: Any) -> None:
        self._meta.update(kv)

    def fetch_meta(self, key: str, default: Any = None) -> Any:
        return self._meta.get(key, default)

    # -- snapshots ----------------------------------------------------------

    def snapshot_index_term(self) -> IdxTerm:
        if self._snapshot is None:
            return IdxTerm(0, 0)
        meta = self._snapshot[0]
        return IdxTerm(meta.index, meta.term)

    def snapshot_meta(self):
        """The current snapshot's metadata (in-memory; no data read)."""
        return self._snapshot[0] if self._snapshot is not None else None

    def checkpoint_index(self) -> int:
        """Newest checkpoint index, 0 if none (ra.hrl:378)."""
        return self._checkpoints[-1][0].index if self._checkpoints else 0

    def snapshot(self) -> Optional[tuple]:
        return self._snapshot

    def update_release_cursor(self, idx: int, cluster: tuple,
                              machine_version: int,
                              machine_state: Any) -> list:
        """Take a snapshot at idx if the entry exists; truncate ≤ idx.
        Memory log does this synchronously (the durable log spawns a
        writer, ra_snapshot.erl:357-398).  Returns effects (none here)."""
        term = self.fetch_term(idx)
        if term is None:
            return []
        meta = SnapshotMeta(index=idx, term=term, cluster=cluster,
                            machine_version=machine_version)
        data = self.snapshot_module.encode(machine_state)
        self._snapshot = (meta, data)
        self.counters["snapshots_written"] += 1
        self.counters["snapshot_bytes_written"] += len(data)
        self._truncate_to_snapshot(idx)
        return []

    def checkpoint(self, idx: int, cluster: tuple, machine_version: int,
                   machine_state: Any) -> list:
        term = self.fetch_term(idx)
        if term is None:
            return []
        meta = SnapshotMeta(index=idx, term=term, cluster=cluster,
                            machine_version=machine_version)
        data = self.snapshot_module.encode(machine_state)
        self._checkpoints.append((meta, data))
        self.counters["checkpoints_written"] += 1
        self.counters["checkpoint_bytes_written"] += len(data)
        # retention: keep at most 10 (ra.hrl:234)
        self._checkpoints = self._checkpoints[-10:]
        return []

    def promote_checkpoint(self, idx: int) -> bool:
        best = None
        for meta, st in self._checkpoints:
            if meta.index <= idx and (best is None or meta.index > best[0].index):
                best = (meta, st)
        if best is None:
            return False
        self._snapshot = best
        self.counters["checkpoints_promoted"] += 1
        self._checkpoints = [c for c in self._checkpoints
                             if c[0].index > best[0].index]
        self._truncate_to_snapshot(best[0].index)
        return True

    # -- chunk-incremental accept (same contract as DurableLog) -------------

    def begin_accept(self, meta: SnapshotMeta) -> None:
        self._accept = (meta, [])

    def accept_chunk(self, data: bytes, chunk_number: int,
                     chunk_crc: int = -1) -> bool:
        a = getattr(self, "_accept", None)
        if a is None:
            return False
        if chunk_number == 1 and a[1]:
            # transfer restarted from the top: drop the partial stream
            a = (a[0], [])
            self._accept = a
        if chunk_crc >= 0:
            import zlib
            if zlib.crc32(data) != chunk_crc:
                self._accept = None
                return False
        a[1].append(data)
        return True

    def complete_accept(self) -> bool:
        a = getattr(self, "_accept", None)
        if a is None:
            return False
        self._accept = None
        self.install_snapshot(a[0], b"".join(a[1]))
        return True

    def abort_accept(self) -> None:
        self._accept = None

    def install_snapshot(self, meta: SnapshotMeta, data: bytes) -> None:
        """Follower side: accept a complete streamed snapshot; truncates the
        whole log below/at the snapshot index (ra_log:install_snapshot)."""
        self.counters["snapshot_installed"] += 1
        self._snapshot = (meta, data)
        self._entries = {i: e for i, e in self._entries.items()
                         if i > meta.index}
        self._first_index = meta.index + 1
        if self._last_index < meta.index:
            self._last_index = meta.index
            self._last_term = meta.term
        self._last_written = IdxTerm(max(self._last_written.index, meta.index),
                                     meta.term if
                                     self._last_written.index <= meta.index
                                     else self._last_written.term)

    def recover_snapshot_state(self) -> Optional[tuple]:
        """Returns (SnapshotMeta, machine_state) or None."""
        if self._snapshot is None:
            return None
        meta, data = self._snapshot
        if not self.snapshot_module.validate(data):
            raise ValueError(
                "snapshot rejected by snapshot module "
                f"{self.snapshot_module.name!r} (format mismatch?)")
        return meta, self.snapshot_module.decode(data)

    # the mock log keeps no checkpoints: the snapshot is the only
    # machine-state base (uniform log interface for server recovery)
    recover_machine_base = recover_snapshot_state

    def snapshot_data(self) -> bytes:
        assert self._snapshot is not None
        return self._snapshot[1]

    def _truncate_to_snapshot(self, idx: int) -> None:
        for i in list(self._entries):
            if i <= idx:
                del self._entries[i]
        self._first_index = idx + 1

    # -- misc ---------------------------------------------------------------

    def tick(self, now_ms: float) -> list:
        return []

    def close(self) -> None:
        pass

    def overview(self) -> dict:
        return {
            "type": "memory",
            "last_index": self._last_index,
            "last_term": self._last_term,
            "first_index": self._first_index,
            "last_written_index_term": tuple(self._last_written),
            "num_entries": len(self._entries),
            "snapshot_index_term": tuple(self.snapshot_index_term()),
        }
