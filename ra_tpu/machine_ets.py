"""Node-owned side tables for machines — the ra_machine_ets role.

The reference runs a hidden gen_server under the top supervisor whose
only job is to OWN ETS tables created on behalf of user machines
(ra_machine_ets.erl:28-33, started from ra_sup.erl:33-35): because the
owner is the long-lived service and not the server process, a machine's
side table survives member crash/restart.  There are no in-tree
callers — it is a service for user machine modules.

Here an Erlang node maps to the Python process, so the registry is
process-global: tables survive server stop/start, supervised restarts,
and RaNode teardown, and are dropped only explicitly (or with the
process).  A "table" is a plain dict — the host-machine analogue of an
ETS set — guarded by the registry lock only for create/delete;
per-table access follows the same discipline as the reference (the
creating machine coordinates its own readers/writers).

Usage from a machine (any callback; typically ``init``, whose config
dict carries the server ``uid``)::

    from ra_tpu import machine_ets

    def init(self, config):
        # scope by uid: two co-hosted clusters picking the same table
        # name get DISTINCT tables instead of silently shared state
        self._tab = machine_ets.create_table("my_index",
                                             scope=config["uid"])
        ...
    # compatibility shim: bare names keep the old process-global
    # behaviour for existing callers (deliberately shared tables)
    tab = machine_ets.create_table("my_machine_index")

Scoped tables are wiped by ``drop_scope(uid)``, which the force-delete
paths call — a deleted member's durable footprint includes its side
tables (the reference deletes a machine's ETS tables with the server's
data the same way).
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

_lock = threading.Lock()
_tables: Dict[str, dict] = {}


def _key(name: str, scope: Optional[str]) -> str:
    # "/" cannot appear in a uid (base64url, RaSystem.validate_uid), so
    # scoped keys can never collide with each other or with bare names
    return f"{scope}/{name}" if scope else name


def create_table(name: str, scope: Optional[str] = None) -> dict:
    """Return the named table, creating it if needed (idempotent — the
    reference's create_table replaces an existing table only because
    ETS errors on duplicate names; machines recreate on restart, so
    keep-existing is the behaviour they actually rely on).  ``scope``
    (typically the server uid from the machine's init config)
    namespaces the name; None keeps the process-global namespace."""
    with _lock:
        return _tables.setdefault(_key(name, scope), {})


def delete_table(name: str, scope: Optional[str] = None) -> None:
    """Drop the named table (no-op if absent)."""
    with _lock:
        _tables.pop(_key(name, scope), None)


def drop_scope(scope: str) -> None:
    """Drop every table created under ``scope`` — the machine-ets half
    of force_delete_server's footprint wipe."""
    if not scope:
        return
    prefix = f"{scope}/"
    with _lock:
        for key in [k for k in _tables if k.startswith(prefix)]:
            del _tables[key]


def which_tables(scope: Optional[str] = None) -> tuple:
    """Names of live tables (overview/debugging).  With ``scope``, the
    bare names under that scope; without, every raw key."""
    with _lock:
        if scope is None:
            return tuple(sorted(_tables))
        prefix = f"{scope}/"
        return tuple(sorted(k[len(prefix):] for k in _tables
                            if k.startswith(prefix)))
