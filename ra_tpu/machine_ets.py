"""Node-owned side tables for machines — the ra_machine_ets role.

The reference runs a hidden gen_server under the top supervisor whose
only job is to OWN ETS tables created on behalf of user machines
(ra_machine_ets.erl:28-33, started from ra_sup.erl:33-35): because the
owner is the long-lived service and not the server process, a machine's
side table survives member crash/restart.  There are no in-tree
callers — it is a service for user machine modules.

Here an Erlang node maps to the Python process, so the registry is
process-global: tables survive server stop/start, supervised restarts,
and RaNode teardown, and are dropped only explicitly (or with the
process).  A "table" is a plain dict — the host-machine analogue of an
ETS set — guarded by the registry lock only for create/delete;
per-table access follows the same discipline as the reference (the
creating machine coordinates its own readers/writers).

Usage from a machine (any callback; typically ``init``)::

    from ra_tpu import machine_ets
    tab = machine_ets.create_table("my_machine_index")
    tab[key] = value          # survives this member's restart
"""
from __future__ import annotations

import threading
from typing import Dict

_lock = threading.Lock()
_tables: Dict[str, dict] = {}


def create_table(name: str) -> dict:
    """Return the named table, creating it if needed (idempotent — the
    reference's create_table replaces an existing table only because
    ETS errors on duplicate names; machines recreate on restart, so
    keep-existing is the behaviour they actually rely on)."""
    with _lock:
        return _tables.setdefault(name, {})


def delete_table(name: str) -> None:
    """Drop the named table (no-op if absent)."""
    with _lock:
        _tables.pop(name, None)


def which_tables() -> tuple:
    """Names of live tables (overview/debugging)."""
    with _lock:
        return tuple(sorted(_tables))
