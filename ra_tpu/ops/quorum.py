"""Batched quorum/commit kernels — the hot Raft arithmetic as XLA ops.

These lift the per-cluster functions of the reference's pure core into
vectorized form over a leading *lane* axis (one lane = one Raft cluster):

* :func:`agreed_commit` — the sorted-median quorum index
  (ra_server.erl:2989-2993 ``agreed_commit``: sort descending, take the
  ``trunc(n/2)+1``-th, 1-based), with voter masking
  (ra_server.erl:2977-2987 ``match_indexes`` skips non-voters).
* :func:`evaluate_quorum` — commit-index advancement with the §5.4.2
  current-term gate (ra_server.erl:2955-2964 ``increment_commit_index``).
  On device the term gate is expressed as ``agreed >= term_start_index``:
  a leader's log tail from its first own-term append onward is entirely in
  the current term, so "entry term == current term" ⟺ "index ≥ index of
  the term-opening noop".
* :func:`election_quorum` — vote counting (ra_server.erl:986-1002 and
  :845-859: win iff granted votes ≥ trunc(voters/2)+1).
* :func:`update_match_next` — the AER-reply success fold
  (ra_server.erl:430-433: match := max(match, last_index),
  next := max(next, next_index)).
* :func:`query_quorum` — consistent-query heartbeat quorum: the agreed
  query index is the same masked median over per-peer confirmed query
  indexes (ra_server.erl:3101-3170, ``query_indexes`` :2966-2976).

All kernels are shape-stable, control-flow-free, and dtype int32 — they
fuse into a handful of VPU ops under jit, and vmap/shard_map cleanly over
the lane axis (sharding spec: lanes are embarrassingly parallel).

Oracle: ra_tpu.core.server.RaServer.agreed_commit and the scalar handlers;
tests/test_ops_quorum.py checks equivalence on randomized cases.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array


def agreed_commit(match_index: Array, voter_mask: Array) -> Array:
    """Quorum-agreed index per lane.

    match_index: int32[..., P] — per-member match indexes; the leader's own
        slot must hold its last *written* index (its fsync confirm counts
        toward the quorum, ra_server.erl:2977-2987).
    voter_mask: bool[..., P] — True for voting members (present + voter).

    Returns int32[...]: the highest index replicated on a majority of
    voters — element ``n//2`` (0-based) of the descending sort, i.e. the
    ``trunc(n/2)+1``-th (1-based) as in the reference.
    """
    # -1 is a sentinel below any valid index (indexes are >= 0)
    masked = jnp.where(voter_mask, match_index, -1)
    sorted_desc = -jnp.sort(-masked, axis=-1)
    n = jnp.sum(voter_mask.astype(jnp.int32), axis=-1)
    k = n // 2
    agreed = jnp.take_along_axis(sorted_desc, k[..., None], axis=-1)[..., 0]
    # lanes with zero voters (unused padding lanes) yield -1 -> clamp to 0
    return jnp.maximum(agreed, 0)


def evaluate_quorum(commit_index: Array, match_index: Array,
                    voter_mask: Array, term_start_index: Array) -> Array:
    """Advance commit_index per lane iff a higher index is quorum-agreed
    AND it lies in the leader's current term (§5.4.2 gate).

    commit_index: int32[...]; match_index: int32[..., P];
    voter_mask: bool[..., P]; term_start_index: int32[...] — index of the
    noop the leader appended when it won its term (ra_server.erl:845-859).
    """
    agreed = agreed_commit(match_index, voter_mask)
    ok = (agreed > commit_index) & (agreed >= term_start_index)
    return jnp.where(ok, agreed, commit_index)


def update_match_next(match_index: Array, next_index: Array,
                      reply_success: Array, reply_last_index: Array,
                      reply_next_index: Array) -> tuple:
    """Fold a batch of successful AER replies into peer state
    (ra_server.erl:430-433).  Failure repair is divergent control flow and
    stays on the host oracle.

    All args broadcast over [..., P]; reply_success masks which slots
    actually replied this step.
    """
    new_match = jnp.where(reply_success,
                          jnp.maximum(match_index, reply_last_index),
                          match_index)
    new_next = jnp.where(reply_success,
                         jnp.maximum(next_index, reply_next_index),
                         next_index)
    return new_match, new_next


def election_quorum(granted_mask: Array, voter_mask: Array) -> Array:
    """True per lane iff granted votes reach trunc(voters/2)+1
    (required_quorum, ra_server.hrl + ra_server.erl:845-859).

    granted_mask must include the candidate's self-vote.
    """
    votes = jnp.sum((granted_mask & voter_mask).astype(jnp.int32), axis=-1)
    needed = jnp.sum(voter_mask.astype(jnp.int32), axis=-1) // 2 + 1
    return votes >= needed


def query_quorum(peer_query_index: Array, voter_mask: Array) -> Array:
    """Agreed (majority-confirmed) consistent-query index per lane.

    peer_query_index: int32[..., P] — per-member confirmed query index,
    with the leader's own value in its slot (it confirms its own
    heartbeats, query_indexes ra_server.erl:2966-2976).  The quorum is
    the same masked median as the commit index.
    """
    return agreed_commit(peer_query_index, voter_mask)


def pipeline_credit(next_index: Array, match_index: Array,
                    last_index: Array, commit_index: Array,
                    commit_index_sent: Array,
                    max_pipeline: int, max_batch: int) -> tuple:
    """Flow-control arithmetic of make_pipelined_rpc_effects
    (ra_server.erl:1862-1918): how many entries to ship to each peer this
    step, bounded by the in-flight window.

    Returns (n_to_send[..., P], needs_rpc[..., P]).
    """
    in_flight = next_index - match_index - 1
    headroom = jnp.maximum(max_pipeline - in_flight, 0)
    avail = jnp.maximum(last_index[..., None] - next_index + 1, 0)
    n = jnp.minimum(jnp.minimum(avail, headroom), max_batch)
    needs = (n > 0) | (commit_index_sent < commit_index[..., None])
    return n, needs
