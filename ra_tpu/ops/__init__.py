from .quorum import (
    agreed_commit,
    election_quorum,
    evaluate_quorum,
    pipeline_credit,
    query_quorum,
    update_match_next,
)
