"""Exact integer one-hot contraction on the MXU.

The TPU's generic per-element gather/scatter lowering is the slowest way
to move per-lane variable-index data; contracting a {0,1} one-hot f32
tensor against the values routes the same movement onto the systolic
array.  f32 accumulation is exact for 16-bit operands, so int32 values
ride as two 16-bit halves (two matmuls) and recombine bitwise —
negatives included, since the (lo | hi<<16) recombination is modular.

Shared by the lockstep engine's ring IO / trajectory select
(engine/lockstep.py) and the machines' vectorized window folds
(models/jit_fifo.py, models/jit_kv.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def split16_matmul(onehot_f32: jax.Array, values: jax.Array) -> jax.Array:
    """Exact int32 gather/scatter-by-matmul: contract a {0,1} one-hot
    f32 tensor [..., A, R] with int32 values [..., R, C] -> [..., A, C].
    Each one-hot row has at most one 1, so every product and sum is
    exact in f32.  Precision.HIGHEST: TPU otherwise lowers f32 matmuls
    through bf16 passes, which silently rounds the 16-bit halves.
    Measured v5e: the engine ring's per-lane variable-index IO costs
    ~15-25ms/step at 10k lanes via the generic gather/scatter
    lowering, ~7ms via this form."""
    lo = (values & 0xFFFF).astype(jnp.float32)
    hi = ((values >> 16) & 0xFFFF).astype(jnp.float32)
    glo = jnp.einsum("...ar,...rc->...ac", onehot_f32, lo,
                     precision=jax.lax.Precision.HIGHEST).astype(jnp.int32)
    ghi = jnp.einsum("...ar,...rc->...ac", onehot_f32, hi,
                     precision=jax.lax.Precision.HIGHEST).astype(jnp.int32)
    return glo | (ghi << 16)


def place16(onehot_f32: jax.Array, values: jax.Array) -> jax.Array:
    """split16_matmul for a value VECTOR: [..., A, R] x [..., R] ->
    [..., A] — the window-fold placement shape."""
    return split16_matmul(onehot_f32, values[..., None])[..., 0]
