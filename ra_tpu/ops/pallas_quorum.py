"""Pallas TPU kernel for the fused quorum/commit step — a DOCUMENTED
EXPERIMENT, off by default.

Status (round-5 measurement, tpu_rows_r05/): the kernel LOSES to the
plain-XLA sort-median lowering on the headline config — 101.4M cmds/s
vs 112.4M (~10% slower).  A hand kernel that trails the compiler is
negative value on the hottest path, so ``auto`` resolution now picks
XLA everywhere; the kernel stays only as a measured baseline for a
future fused quorum+credit+clamp attempt.  Opt back in with
``RA_TPU_ENABLE_PALLAS_QUORUM=1`` (or an explicit ``impl="pallas"``).
The measured gap is recorded in docs/BENCHMARKS.md.

The hot per-step arithmetic of the lockstep engine is
``evaluate_quorum`` (ra_tpu.ops.quorum): a voter-masked majority median
over the per-member match indexes, the §5.4.2 term gate, and the
commit-index monotonicity clamp (ra_server.erl:2941-2993).  The jnp
reference implementation lowers the median through a generic sort; this
kernel instead uses a **count-based selection** — for tiny member counts
(P <= 15) the quorum-agreed index is

    max over voters i of  match[i]  such that
        #{ voters j : match[j] >= match[i] }  >=  trunc(n/2)+1

which is an O(P^2) pairwise-compare reduction: pure VPU work with no
sort, fused with the gate in one VMEM pass over the lane axis.

Layout: lanes ride the 128-wide lane axis; the member axis is padded to
the int32 sublane tile (8).  The wrapper transposes/pads [N,P] inputs —
XLA fuses that into the surrounding program.

Equivalence against the jnp oracle: tests/test_pallas_quorum.py (runs
the kernel in interpreter mode off-TPU).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

Array = jax.Array

_LANE_TILE = 512     # lanes per grid step (multiple of 128)
_SUBLANE = 8         # int32 sublane tile


def _kernel(commit_ref, match_ref, voter_ref, tstart_ref, out_ref):
    match = match_ref[:]                    # [P8, T] int32
    voter = voter_ref[:]                    # [P8, T] int32 (0/1)
    commit = commit_ref[:]                  # [1, T]  int32
    tstart = tstart_ref[:]                  # [1, T]  int32
    masked = jnp.where(voter > 0, match, -1)
    n = jnp.sum(voter, axis=0, keepdims=True)            # [1, T]
    needed = n // 2 + 1
    # support_i = #{ voters j : match_j >= match_i }; pairwise over the
    # (tiny, padded) member axis
    ge = (masked[None, :, :] >= masked[:, None, :]).astype(jnp.int32)
    support = jnp.sum(ge * voter[None, :, :], axis=1)    # [P8, T]
    cand = jnp.where((support >= needed) & (voter > 0), masked, -1)
    agreed = jnp.maximum(jnp.max(cand, axis=0, keepdims=True), 0)  # [1, T]
    ok = (agreed > commit) & (agreed >= tstart)
    out_ref[:] = jnp.where(ok, agreed, commit)


@functools.partial(jax.jit, static_argnames=("interpret",))
def evaluate_quorum_pallas(commit_index: Array, match_index: Array,
                           voter_mask: Array, term_start_index: Array,
                           interpret: bool = False) -> Array:
    """Drop-in replacement for ops.quorum.evaluate_quorum.

    commit_index: int32[N]; match_index: int32[N, P];
    voter_mask: bool[N, P]; term_start_index: int32[N].
    """
    from jax.experimental import pallas as pl

    N, P = match_index.shape
    n_pad = (-N) % _LANE_TILE
    p_pad = (-P) % _SUBLANE
    # transpose to [P8, Npad]: members on sublanes, lanes on the lane axis
    match_t = jnp.pad(match_index.T.astype(jnp.int32),
                      ((0, p_pad), (0, n_pad)))
    voter_t = jnp.pad(voter_mask.T.astype(jnp.int32),
                      ((0, p_pad), (0, n_pad)))
    commit_t = jnp.pad(commit_index.astype(jnp.int32),
                       ((0, n_pad),))[None, :]
    tstart_t = jnp.pad(term_start_index.astype(jnp.int32),
                       ((0, n_pad),))[None, :]
    Np = N + n_pad
    Pp = P + p_pad
    grid = (Np // _LANE_TILE,)
    lane_block = lambda rows: pl.BlockSpec(  # noqa: E731
        (rows, _LANE_TILE), lambda i: (0, i))
    out = pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((1, Np), jnp.int32),
        grid=grid,
        in_specs=[lane_block(1), lane_block(Pp), lane_block(Pp),
                  lane_block(1)],
        out_specs=lane_block(1),
        interpret=interpret,
    )(commit_t, match_t, voter_t, tstart_t)
    return out[0, :N]


def make_evaluate_quorum(impl: str = "auto"):
    """Resolve the quorum implementation: 'xla' (jnp sort-median oracle),
    'pallas' (this kernel), or 'auto'.  'auto' resolves to XLA — the
    kernel measured ~10% SLOWER than the compiler on the headline
    config (101.4M vs 112.4M cmds/s, round 5), so it is demoted to an
    env-gated experiment: set RA_TPU_ENABLE_PALLAS_QUORUM=1 to let
    'auto' pick it on TPU backends again (an explicit 'pallas' always
    wins)."""
    import os

    from .quorum import evaluate_quorum as xla_impl

    if impl == "auto":
        gate = os.environ.get("RA_TPU_ENABLE_PALLAS_QUORUM", "")
        impl = "pallas" if gate not in ("", "0") and \
            jax.default_backend() in ("tpu", "axon") else "xla"
    if impl == "pallas":
        # off-TPU the kernel only runs under the interpreter; resolve at
        # build time so an explicit 'pallas' choice works on a dev box
        # instead of failing to lower at the first step()
        interpret = jax.default_backend() not in ("tpu", "axon")
        return lambda c, m, v, t: evaluate_quorum_pallas(
            c, m, v, t, interpret=interpret)
    return xla_impl
