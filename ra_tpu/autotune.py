"""Closed-loop SLO autotuner for the lane-engine pipeline (ISSUE 9).

The second half of the loop the Observatory ring was built for: a
hysteresis-bounded controller that reads the SLO engine's verdicts
plus the phase attribution's per-window budget shares and adapts the
pipeline knobs BETWEEN dispatches — never inside one (the controller
runs on the host at window cadence; the jitted step is untouched, so
rule RA04 holds by construction).

Knobs (``TUNABLE_KNOBS``, each stamped in the ``engine_pipeline``
overview — rule RA07: no silent knob turns):

* ``superstep_k`` — engine rounds fused per XLA dispatch.  Raised when
  the window is DISPATCH-BOUND (the ``device_dispatch``/``host_staging``
  phases own the budget): more fusion amortizes the fixed dispatch
  cost.  Lowered when fsync-bound and the batch interval is already at
  its floor: fewer rounds per dispatch shrinks the per-dispatch WAL
  burst the fsync path must absorb.
* ``cmds_per_step`` — per-lane batch depth.  Raised on a throughput
  breach whose latency objectives are green (batching headroom).
* ``wal_max_batch_interval_ms`` — the WAL group-commit wait budget.
  Backed off (halved toward 0) when the window is FSYNC-BOUND: a
  forced group wait on a slow disk only adds confirm latency.

Control discipline (docs/INTERNALS.md §11):

* **hysteresis** — an objective must breach ``breach_windows``
  consecutive ticks before any knob moves; one green tick resets the
  streak.  A single noisy window never turns a knob.
* **bounded steps** — every move is a factor-of-two (or one halving of
  the interval), clamped to per-knob bounds; the controller can only
  walk the knob space, never jump it.
* **cooldown** — ``cooldown_windows`` ticks after a decision before
  the next: each move's effect must land in the ring before it can be
  judged.
* **hard freeze** — while any transport FaultPlan or DiskFaultPlan is
  active, or an incident bundle was dumped within
  ``incident_freeze_s``: a controller must never chase chaos-injected
  or crash-transient latency with knob turns.  Freeze transitions are
  recorded (``tune.freeze``).

Every decision is a registered flight-recorder event
(``tune.decision``) carrying knob, old→new, triggering phase and
objective — ``tools/ra_trace.py`` and the ra_top footer can always
reconstruct "why did K change".

The tuner does not own the dispatch loop: drivers read the live knob
values from :attr:`AutoTuner.knobs` between dispatches (the bench's
opt-in fused autotune mode restages its superstep block when K moves,
and the closed-loop tests drive the same contract);
``wal_max_batch_interval_ms`` is additionally pushed straight into the
live WAL shards via ``EngineDurability.set_batch_interval_ms``.  A
loop that CANNOT apply a knob must freeze it via ``bounds`` (pin lo ==
hi) — a recorded decision that changes nothing measured would turn
the knob stamps into lies.
"""
from __future__ import annotations

import collections
import time
from typing import Callable, Optional

from .blackbox import RECORDER, record

#: every knob this controller may turn — rule RA07 requires each to be
#: stamped in the engine_pipeline overview (telemetry.py engine source)
#: and documented in docs/OBSERVABILITY.md
TUNABLE_KNOBS = ("superstep_k", "cmds_per_step",
                 "wal_max_batch_interval_ms")

#: per-knob (lo, hi) clamp — bounded step size means a decision can
#: only double/halve within these
DEFAULT_BOUNDS = {
    "superstep_k": (1, 64),
    "cmds_per_step": (1, 1024),
    "wal_max_batch_interval_ms": (0.0, 50.0),
}

#: phases whose budget dominance reads as DISPATCH-BOUND (fixed
#: dispatch overhead amortizable by fusion) vs FSYNC-BOUND (durability
#: syscall path; fusion makes it worse, back off instead)
DISPATCH_BOUND_PHASES = ("device_dispatch", "host_staging",
                         "queue_wait", "wal_encode")
FSYNC_BOUND_PHASES = ("fsync_wait", "confirm_publish")

DEFAULT_COOLDOWN_WINDOWS = 3
DEFAULT_BREACH_WINDOWS = 2
DEFAULT_INCIDENT_FREEZE_S = 30.0
#: decision freeze horizon after the device-plane compile counter
#: moves (ISSUE 16): a knob change that triggers recompilation must
#: not be read as a latency regression mid-compile — the retraced
#: variant's warm windows need to flush through the ring first
DEFAULT_COMPILE_FREEZE_S = 10.0


def default_freeze_guard() -> Optional[str]:
    """The standard freeze predicate: an INSTALLED DiskFaultPlan, or
    any live transport FaultPlan that can still inject (``quiet()``
    plans — all-zero probabilities, partitions healed — do not count:
    routers keep their plan object after a chaos exercise ends, and
    mere liveness must not freeze the controller for the rest of the
    process).  Returns a reason string or None; the incident-freshness
    half lives in the tuner, which owns the horizon."""
    from .log import faults
    if faults.current_plan() is not None:
        return "disk_fault_plan_active"
    from .transport.rpc import live_fault_plans
    if any(not p.quiet() for p in live_fault_plans()):
        return "transport_fault_plan_active"
    return None


class AutoTuner:
    """Hysteresis-bounded closed-loop controller over SLO verdicts +
    phase attribution.  Call :meth:`tick` at window cadence (between
    dispatches / at snapshot boundaries)."""

    def __init__(self, slo, observatory=None, *, durability=None,
                 knobs: Optional[dict] = None,
                 bounds: Optional[dict] = None,
                 cooldown_windows: int = DEFAULT_COOLDOWN_WINDOWS,
                 breach_windows: int = DEFAULT_BREACH_WINDOWS,
                 incident_freeze_s: float = DEFAULT_INCIDENT_FREEZE_S,
                 compile_freeze_s: float = DEFAULT_COMPILE_FREEZE_S,
                 freeze_guard: Callable[[], Optional[str]] =
                 default_freeze_guard,
                 apply: Optional[dict] = None) -> None:
        self.slo = slo
        self.obs = observatory if observatory is not None else slo.obs
        self.dur = durability
        self.bounds = {**DEFAULT_BOUNDS, **(bounds or {})}
        #: live knob values — dispatch loops read these between
        #: dispatches; seeded from the durability bridge where known
        self.knobs = {
            "superstep_k": 1,
            "cmds_per_step": 32,
            "wal_max_batch_interval_ms":
                durability.batch_interval_ms()
                if durability is not None else 0.0,
        }
        if knobs:
            unknown = set(knobs) - set(TUNABLE_KNOBS)
            if unknown:
                raise ValueError(f"unknown knobs: {sorted(unknown)}")
            self.knobs.update(knobs)
        self.cooldown_windows = max(0, int(cooldown_windows))
        self.breach_windows = max(1, int(breach_windows))
        self.incident_freeze_s = float(incident_freeze_s)
        self.compile_freeze_s = float(compile_freeze_s)
        #: compile-storm state: the devicewatch compile count last seen
        #: (None until the first tick baselines it — warm-up compiles
        #: that happened before the controller existed are not a storm)
        self._compiles_seen: Optional[int] = None
        self._compile_quiet_until = 0.0
        self._freeze_guard = freeze_guard
        self._apply_hooks = dict(apply or {})
        self._breach_streak: dict = {}
        self._cooldown_left = 0
        self._frozen_reason: Optional[str] = None
        #: bounded, like every long-lived record in this repo — a
        #: controller alternating regimes for days must not grow a
        #: list (the full decision history is in the flight recorder)
        self.decisions: collections.deque = collections.deque(maxlen=256)
        self.ticks = 0
        self.freezes = 0
        if self.obs is not None:
            self.obs.add_source("autotune", self.overview)

    # -- freeze guards -----------------------------------------------------

    def _freeze_reason(self) -> Optional[str]:
        reason = self._freeze_guard() if self._freeze_guard else None
        if reason is not None:
            return reason
        inc = RECORDER.last_incident()
        if inc is not None and \
                time.time() - inc.get("ts", 0.0) < self.incident_freeze_s:
            return "recent_incident"
        return self._compile_storm_reason()

    def _compile_storm_reason(self) -> Optional[str]:
        """Freeze while the device plane is (re)compiling (ISSUE 16):
        when the recompile sentinel's compile counter moves between
        ticks, decisions suspend for ``compile_freeze_s`` — the
        windows spanning a compile carry its wall time as latency and
        must not be chased with knob turns.  Host dict reads only (the
        tick path is RA04-gated)."""
        try:
            from .devicewatch import WATCH
            seen = WATCH.counters["compiles"]
        except Exception:  # noqa: BLE001 — devicewatch unavailable
            return None
        if self._compiles_seen is None:
            self._compiles_seen = seen
            return None
        if seen > self._compiles_seen:
            self._compiles_seen = seen
            self._compile_quiet_until = time.time() + self.compile_freeze_s
            return "compile_storm"
        if time.time() < self._compile_quiet_until:
            return "compile_storm"
        return None

    # -- phase attribution -------------------------------------------------

    def _dominant_phase(self) -> tuple:
        """The phase owning the largest share of the newest window's
        budget: per-window deltas of the monotone per-phase
        ``total_ms`` counters from the ring (the PHASE_FIELDS
        attribution).  Returns (phase, share) or (None, 0.0)."""
        rates = self.obs.window_rates()
        pre, suf = "engine_phases_", "_total_ms"
        shares = {k[len(pre):-len(suf)]: v for k, v in rates.items()
                  if k.startswith(pre) and k.endswith(suf) and v > 0}
        # commit_e2e SPANS the others (submit->confirm covers queue/
        # encode/fsync/confirm); it is the SLO's latency signal, not a
        # budget component — attributing to it would always win
        shares.pop("commit_e2e", None)
        if not shares:
            return None, 0.0
        total = sum(shares.values())
        phase = max(shares, key=lambda p: shares[p])
        return phase, shares[phase] / total if total > 0 else 0.0

    # -- decision ----------------------------------------------------------

    def _set(self, knob: str, new, *, phase, objective) -> dict:
        lo, hi = self.bounds[knob]
        new = min(hi, max(lo, new))
        old = self.knobs[knob]
        decision = {"ts": time.time(), "knob": knob, "old": old,
                    "new": new, "phase": phase, "objective": objective,
                    "tick": self.ticks}
        self.knobs[knob] = new
        if knob == "wal_max_batch_interval_ms" and self.dur is not None:
            # live push: the WAL batch threads read the interval per
            # group, so the change lands at the next batch
            self.dur.set_batch_interval_ms(new)
        hook = self._apply_hooks.get(knob)
        if hook is not None:
            hook(new)
        self.decisions.append(decision)
        record("tune.decision", knob=knob, old=old, new=new,
               phase=phase or "?", objective=objective or "?",
               tick=self.ticks)
        return decision

    def _streak(self, verdicts: dict, name: str) -> int:
        """Consecutive breach-tick count for an objective (hysteresis
        state); updated per tick from the verdict."""
        obj = verdicts.get("objectives", {}).get(name)
        bad = obj is not None and not obj["ok"]
        self._breach_streak[name] = \
            self._breach_streak.get(name, 0) + 1 if bad else 0
        return self._breach_streak[name]

    def tick(self) -> Optional[dict]:
        """One controller window: evaluate freeze guards, verdicts and
        phase shares; make AT MOST one bounded decision.  Returns the
        decision dict or None (frozen / cooling down / all green /
        knob already at its bound)."""
        self.ticks += 1
        reason = self._freeze_reason()
        if reason is not None:
            if self._frozen_reason is None:
                # record the TRANSITION, not every frozen tick — the
                # freeze can outlast thousands of windows
                self.freezes += 1
                record("tune.freeze", reason=reason, tick=self.ticks)
            self._frozen_reason = reason
            # hysteresis state resets: post-freeze windows must prove
            # a breach afresh (fault-era breaches are not evidence)
            self._breach_streak.clear()
            return None
        self._frozen_reason = None
        verdicts = self.slo.evaluate()
        streaks = {name: self._streak(verdicts, name)
                   for name in verdicts.get("objectives", {})}
        if self._cooldown_left > 0:
            self._cooldown_left -= 1
            return None
        decision = self._decide(verdicts, streaks)
        if decision is not None:
            self._cooldown_left = self.cooldown_windows
        return decision

    def _decide(self, verdicts: dict, streaks: dict) -> Optional[dict]:
        objs = verdicts.get("objectives", {})

        def hot(name: str) -> bool:
            return streaks.get(name, 0) >= self.breach_windows

        k = self.knobs["superstep_k"]
        interval = self.knobs["wal_max_batch_interval_ms"]
        # read_p99_ms is handled by its own trade rule below: a read
        # breach must never read as a WRITE latency signal (it would
        # back off the WAL or deepen fusion — both wrong for reads)
        lat_hot = [n for n, o in objs.items()
                   if o["op"] == "<=" and hot(n) and n != "read_p99_ms"]
        # fsync-bound: the fsync objective itself burns, or a latency
        # breach whose window budget the fsync phases own
        phase, share = self._dominant_phase()
        fsync_bound = hot("fsync_p99_ms") or (
            bool(lat_hot) and phase in FSYNC_BOUND_PHASES)
        if fsync_bound:
            trigger = "fsync_p99_ms" if hot("fsync_p99_ms") \
                else lat_hot[0]
            tphase = phase if phase in FSYNC_BOUND_PHASES \
                else "fsync_wait"
            if interval > self.bounds["wal_max_batch_interval_ms"][0]:
                # back off the group-commit wait budget first: it is
                # pure added confirm latency on a slow disk (<=1ms
                # rounds to 0 — a sub-ms wait budget is noise)
                new = 0.0 if interval <= 1.0 else round(interval / 2, 3)
                return self._set("wal_max_batch_interval_ms", new,
                                 phase=tphase, objective=trigger)
            if k > self.bounds["superstep_k"][0]:
                # interval at floor: shrink the per-dispatch WAL burst
                return self._set("superstep_k", max(1, k // 2),
                                 phase=tphase, objective=trigger)
            return None
        # read/write trade (ISSUE 20): a read-latency breach with the
        # write plane green means each fused dispatch is too LONG for
        # the read confirm schedule — a pending read batch waits O(K)
        # inner rounds for its commit-watermark confirmation before the
        # next window boundary observes it.  Halve the fusion depth so
        # reads settle sooner; if the throughput floor then burns, the
        # headroom rule below wins the fusion back — the two rules
        # walking K against each other IS the read/write trade, and
        # hysteresis + cooldown keep the walk damped.
        if hot("read_p99_ms") and not lat_hot:
            if k > self.bounds["superstep_k"][0]:
                return self._set("superstep_k", max(1, k // 2),
                                 phase="read_e2e",
                                 objective="read_p99_ms")
            return None
        if lat_hot and phase in DISPATCH_BOUND_PHASES:
            # dispatch-bound latency: fuse more rounds per dispatch
            if k < self.bounds["superstep_k"][1]:
                return self._set("superstep_k", k * 2, phase=phase,
                                 objective=lat_hot[0])
            return None
        thr_hot = [n for n, o in objs.items()
                   if o["op"] == ">=" and hot(n)]
        if thr_hot and not lat_hot:
            # throughput floor burning with green latency: spend the
            # latency headroom — deepen fusion first (amortize
            # dispatch), then the per-lane batch
            if k < self.bounds["superstep_k"][1]:
                return self._set("superstep_k", k * 2,
                                 phase=phase or "device_dispatch",
                                 objective=thr_hot[0])
            c = self.knobs["cmds_per_step"]
            if c < self.bounds["cmds_per_step"][1]:
                return self._set("cmds_per_step", c * 2,
                                 phase=phase or "device_dispatch",
                                 objective=thr_hot[0])
        return None

    # -- observability -----------------------------------------------------

    def overview(self) -> dict:
        """What the Observatory ``autotune`` source embeds and ra_top's
        footer renders: live knob values (RA07's stamp), freeze state,
        and the newest decision."""
        last = self.decisions[-1] if self.decisions else None
        return {
            "knobs": {
                "superstep_k": self.knobs["superstep_k"],
                "cmds_per_step": self.knobs["cmds_per_step"],
                "wal_max_batch_interval_ms":
                    self.knobs["wal_max_batch_interval_ms"],
            },
            "frozen": self._frozen_reason is not None,
            "freeze_reason": self._frozen_reason,
            "freezes": self.freezes,
            "ticks": self.ticks,
            "decisions": len(self.decisions),
            "cooldown_left": self._cooldown_left,
            "last_decision": dict(last) if last else None,
        }
