"""Offline log replay for debugging — the ra_dbg role
(/root/reference/src/ra_dbg.erl:26-55): fold a server's persisted log
through a machine without starting any runtime, deduping overwritten
indexes the same way WAL recovery does.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Optional

from .core.machine import ApplyMeta, Machine
from .core.types import Entry, UserCommand
from .log.durable import _read_snapshot_file, decode_command
from .log.snapshot import DEFAULT_SNAPSHOT_MODULE
from .log.segment import SegmentFile
from .log.wal import scan_wal_file


def read_log(data_dir: str, uid: str, snapshot_module=None) -> tuple:
    """Collect (snapshot, ordered entries) for a server from its on-disk
    state: snapshot + segments + surviving WAL files."""
    server_dir = os.path.join(data_dir, uid)
    snapshot = None
    snapdir = os.path.join(server_dir, "snapshot")
    if os.path.isdir(snapdir):
        for fname in sorted(os.listdir(snapdir), reverse=True):
            got = _read_snapshot_file(os.path.join(snapdir, fname))
            if got is not None:
                mod = snapshot_module or DEFAULT_SNAPSHOT_MODULE
                snapshot = (got[0], mod.decode(got[1]))
                break
    entries: dict[int, tuple] = {}
    if os.path.isdir(server_dir):
        for fname in sorted(os.listdir(server_dir)):
            if not fname.endswith(".segment"):
                continue
            seg = SegmentFile(os.path.join(server_dir, fname))
            r = seg.range()
            if r is not None:
                for idx in range(r[0], r[1] + 1):
                    got = seg.read(idx)
                    if got is not None:
                        entries[idx] = got
            seg.close()
    waldir = os.path.join(data_dir, "wal")
    tables: dict = {}
    if os.path.isdir(waldir):
        for fname in sorted(f for f in os.listdir(waldir)
                            if f.endswith(".wal")):
            try:
                scan_wal_file(os.path.join(waldir, fname), tables)
            except Exception:
                pass  # torn tail: keep the prefix
    for idx, (term, payload) in tables.get(uid, {}).items():
        entries[idx] = (term, payload)
    snap_idx = snapshot[0].index if snapshot else 0
    ordered = [Entry(i, entries[i][0], decode_command(entries[i][1]))
               for i in sorted(entries) if i > snap_idx]
    return snapshot, ordered


def replay_log(data_dir: str, uid: str, machine: Machine,
               on_entry: Optional[Callable] = None) -> Any:
    """Replay a server's committed-on-disk log through ``machine`` and
    return the final machine state (replay_log/3, ra_dbg.erl:26-55)."""
    # the machine's snapshot module decodes its own state format
    # (snapshot_module/0 override, ra_machine.erl:435-437)
    snapshot, entries = read_log(data_dir, uid,
                                 snapshot_module=machine.snapshot_module())
    if snapshot is not None:
        state = snapshot[1]
    else:
        state = machine.init({"uid": uid, "dbg": True})
    for e in entries:
        if isinstance(e.command, UserCommand):
            meta = ApplyMeta(index=e.index, term=e.term)
            result = machine.apply(meta, e.command.data, state)
            state = result[0]
        # noop/membership entries don't touch machine state
        if on_entry is not None:
            on_entry(e, state)
    return state
