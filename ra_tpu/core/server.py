"""Pure Raft core — the ra_server equivalent.

This is the host-side *oracle* implementation of the per-cluster Raft state
machine: every transition mirrors the semantics of
/root/reference/src/ra_server.erl (cited per-function below) but is written
as a Python class whose handlers are ``(event) -> effects`` with the next
Raft state recorded in ``self.raft_state``.  Side effects are **data**
(ra_tpu.core.types effect dataclasses) executed by the shell
(ra_tpu.proc.ServerProcess) — the same purity contract as the reference,
which is what lets the hot arithmetic (quorum evaluation, vote counting,
heartbeat quorum) also be implemented as batched XLA kernels in ra_tpu.ops:
the lane engine keeps thousands of these cores' *hot fields* in SoA arrays
and uses this class only for rare/divergent transitions and as the
conformance oracle for kernel tests.

Design note (TPU-first): nothing in this module performs I/O or blocks.  The
log is an injected object with memtable semantics; durability is observed
only through WrittenEvent messages, so a leader's own fsync participates in
the commit quorum exactly like a follower's reply (ra_server.erl:2977-2993).
"""
from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Optional

from ..blackbox import record
from .machine import ApplyMeta, Machine
from .types import (
    RA_PROTO_VERSION,
    AppendEffect,
    AppendEntriesReply,
    AppendEntriesRpc,
    AuxCommandEvent,
    AuxEffect,
    CancelElectionTimeout,
    Checkpoint,
    ClusterChangeCommand,
    ClusterDeleteCommand,
    CommandEvent,
    CommandsEvent,
    CommandResult,
    ConsistentQueryEvent,
    DownEvent,
    ElectionTimeout,
    Entry,
    ErrorResult,
    ForceElectionEvent,
    ForceMemberChangeEvent,
    GarbageCollection,
    HeartbeatReply,
    HeartbeatRpc,
    IdxTerm,
    InstallSnapshotResult,
    InstallSnapshotRpc,
    JoinCommand,
    LeaveCommand,
    LogReadEffect,
    Membership,
    Monitor,
    NextEvent,
    NodeEvent,
    NoopCommand,
    Notify,
    PeerStatus,
    PreVoteResult,
    PreVoteRpc,
    PromoteCheckpoint,
    RaftState,
    RecordLeader,
    ReleaseCursor,
    Reply,
    ReplyMode,
    RequestVoteResult,
    RequestVoteRpc,
    SendMsg,
    SendRpc,
    SendSnapshot,
    SendVoteRequests,
    ServerConfig,
    ServerId,
    SnapshotMeta,
    StartElectionTimeout,
    TickEvent,
    TransferLeadershipEvent,
    UpEvent,
    UserCommand,
    WalUpEvent,
    WrittenEvent,
)


@dataclass
class Peer:
    """Per-peer replication state (ra.hrl:62-73, new_peer ra_server.erl:3006)."""

    next_index: int = 1
    match_index: int = 0
    commit_index_sent: int = 0
    query_index: int = 0
    status: PeerStatus = PeerStatus.NORMAL
    membership: Membership = Membership.VOTER
    promote_target: int = 0  # promotable non-voter: target index
    snapshot_sender: Any = None  # token of in-flight snapshot send
    snapshot_started: float = 0.0  # when SENDING_SNAPSHOT was entered


@dataclass
class Condition:
    """await_condition descriptor (ra_server.erl await_condition state)."""

    predicate: Callable  # (event, server) -> bool
    transition_to: RaftState = RaftState.FOLLOWER
    timeout_ms: Optional[int] = None
    timeout_effects: list = field(default_factory=list)


#: backlog cap for events postponed during await_condition; beyond it the
#: oldest postponed event is dropped (the leader resends — same recovery
#: as the pre-postpone behaviour, just rarer)
MAX_CONDITION_PENDING = 1024


class RaServer:
    """One cluster member's pure Raft core."""

    def __init__(self, config: ServerConfig, log) -> None:
        self.cfg = config
        self.log = log
        self.id: ServerId = config.server_id
        self.machine: Machine = config.machine
        # machine-selected snapshot format (snapshot_module/0 override,
        # ra_machine.erl:435-437; behaviour ra_snapshot.erl:98-168)
        if config.machine is not None:
            mod = config.machine.snapshot_module()
            if mod is not None:
                log.snapshot_module = mod

        # persisted via the log's meta store (ra_log_meta)
        self.current_term: int = log.fetch_meta("current_term", 0)
        self.voted_for: Optional[ServerId] = log.fetch_meta("voted_for")

        self.raft_state: RaftState = RaftState.RECOVER
        self.leader_id: Optional[ServerId] = None
        self.commit_index: int = 0
        self.last_applied: int = log.fetch_meta("last_applied", 0)

        # machine versioning (ra_server.erl init + noop handling :2671-2732)
        self.machine_version: int = self.machine.version
        self.effective_machine_version: int = 0
        self.effective_machine: Machine = self.machine.which_module(0)
        self.machine_versions: list = []  # [(idx, version)] newest first

        self.cluster: dict[ServerId, Peer] = {}
        self.cluster_change_permitted: bool = False
        self.cluster_index_term: IdxTerm = IdxTerm(0, 0)
        self.previous_cluster: Optional[tuple] = None
        self.membership: Membership = config.membership

        self.votes: int = 0
        self.pre_vote_token: Any = None
        self.condition: Optional[Condition] = None
        #: events postponed while in await_condition, replayed on exit
        self.condition_pending: deque = deque()

        # consistent-query machinery (ra_server.erl:3032-3190)
        self.query_index: int = 0
        self.queries_waiting_heartbeats: list = []  # [(qidx, from, fun, ci)]
        self.pending_consistent_queries: list = []  # [(from, fun, ci)]
        # memo for _cluster_spec_at's downward scan: (idx, spec) == "the
        # newest cluster change at/below idx resolves to spec".  Entries
        # at/below a release cursor are committed and immutable, so a
        # cached answer never goes stale; it only ever narrows the scan.
        self._spec_cache: Optional[tuple] = None

        self.machine_state: Any = None
        self.aux_state: Any = self.machine.init_aux(config.uid)
        self.commit_latency: float = 0.0
        #: core-owned counters (merged into key_metrics by the shell);
        #: plain dict so the core stays free of registry dependencies.
        #: aer_batches_sent / aer_batch_entries are the leader-side
        #: replication-batching health pair (ISSUE 13): entries/batches
        #: is the realized AER batching factor
        self.stats: dict = {"term_and_voted_for_updates": 0,
                            "aer_batches_sent": 0, "aer_batch_entries": 0}
        #: bounded reservoir of recent AER batch sizes — the p50/p99
        #: substrate of RaNode.classic_stats() (CLASSIC_FIELDS)
        self._aer_batch_sizes: deque = deque(maxlen=512)
        #: batch-append fast paths when the log implements them (the
        #: durable + memory logs do; bare mocks fall back per-entry)
        self._log_append_batch = getattr(log, "append_batch", None)
        self._log_read_payloads = getattr(log, "read_range_with_payloads",
                                          None)
        self._transfer_target: Optional[ServerId] = None
        #: SnapshotMeta of an in-flight chunked install (the log owns the
        #: streamed bytes; the core only tracks which snapshot it is)
        self._accepting_snapshot: Optional[SnapshotMeta] = None
        self._persisted_last_applied: int = self.last_applied
        self._last_meta_save: float = 0.0  # throttle clock for the above

        self._init_state()

    # ------------------------------------------------------------------
    # init / recovery (ra_server.erl:249-414)
    # ------------------------------------------------------------------

    def _init_state(self) -> None:
        # persisted apply progress (lazy, ra_log_meta) marks entries as
        # known-committed; the machine state itself is rebuilt from the
        # snapshot base by re-applying them with effects suppressed
        persisted_la = self.last_applied
        # machine-state base: the newest valid of {snapshot, checkpoints}
        # (ra_snapshot:init, ra_snapshot.erl:183-222) — checkpoints cut
        # the replay span without truncating the log
        snap = self.log.recover_machine_base()
        if snap is not None:
            meta, mac_state = snap
            self.machine_state = mac_state
            base = meta.index
            self.effective_machine_version = meta.machine_version
            self.effective_machine = self.machine.which_module(
                meta.machine_version)
            self.machine_versions = [(meta.index, meta.machine_version)]
            self.cluster = {sid: Peer(membership=m)
                            for sid, m in meta.cluster}
            # the recovered config is as-of the snapshot point (same
            # rationale as the install path: cit must not stay 0 or the
            # config-voter fallback misfires for servers absent from
            # the snapshot's cluster)
            self.cluster_index_term = IdxTerm(meta.index, meta.term)
        else:
            self.machine_state = self.machine.init(
                {"id": self.id, "uid": self.cfg.uid,
                 "name": self.cfg.cluster_name})
            base = 0
            self.cluster = {sid: Peer() for sid in self.cfg.initial_members}
            self.machine_versions = [(0, 0)]
        if self.id not in self.cluster and not self.cluster:
            self.cluster[self.id] = Peer()
        self.membership = self._get_membership()
        self.last_applied = base
        # commit index resumes at the persisted apply watermark; recover()
        # replays (base, commit_index] (ra_server.erl:305-320, 376-414)
        self.commit_index = max(base, persisted_la)

    def recover(self) -> list:
        """Replay committed-but-unapplied entries with effects suppressed
        (deduped by persisted last_applied), then scan the remainder of the
        log for cluster changes only (ra_server.erl:376-414)."""
        effects: list = []
        self._apply_to(self.commit_index, effects, suppress=True)
        # scan the un-committed tail for cluster changes (cluster_scan_fun)
        last_idx, _ = self.log.last_index_term()
        for entry in self.log.read_range(self.last_applied + 1, last_idx):
            cmd = entry.command
            if isinstance(cmd, ClusterChangeCommand):
                # record the revert baseline (an overwrite of this
                # uncommitted change after restart must restore it) and
                # order cit before _set_cluster so the cached membership
                # sees the new index (its config fallback keys on cit==0)
                self.previous_cluster = (
                    self.cluster_index_term,
                    tuple((sid, p.membership)
                          for sid, p in self.cluster.items()))
                self.cluster_index_term = IdxTerm(entry.index, entry.term)
                self._set_cluster(dict_from_cluster_spec(cmd.cluster))
        self.raft_state = RaftState.FOLLOWER
        return []

    # ------------------------------------------------------------------
    # public dispatch
    # ------------------------------------------------------------------

    def handle(self, event: Any) -> list:
        """Dispatch one event; NextEvent effects are resolved inline (they
        are the core's own re-injections, ra_server_proc's next_event), so
        callers only ever see external effects."""
        effects = self._dispatch(event)
        effects = self._convert_append_effects(effects)
        return self._resolve_next_events(effects)

    def _convert_append_effects(self, effects: list) -> list:
        """{append, Cmd} machine effects re-enter the command path on the
        leader (ra_server_proc.erl:1377-1382) — from ANY machine callback
        (apply, tick, state_enter, version bump).  A WAL-parked leader
        (await_condition -> leader) converts too: the command event is
        then postponed/replayed by the condition machinery like any other
        client command.  Non-leaders drop the effect
        (filter_follower_effects: only the leader originates the append;
        members receive it through replication)."""
        if not any(isinstance(e, AppendEffect) for e in effects):
            return effects
        is_leader = self.raft_state == RaftState.LEADER or \
            (self.raft_state == RaftState.AWAIT_CONDITION and
             self.condition is not None and
             self.condition.transition_to == RaftState.LEADER)
        out: list = []
        for e in effects:
            if isinstance(e, AppendEffect):
                if is_leader:
                    mode = e.reply_mode or ReplyMode.NOREPLY
                    follow = UserCommand(data=e.data, reply_mode=mode,
                                         correlation=e.correlation,
                                         notify_to=e.notify_to)
                    out.append(NextEvent(CommandEvent(follow)))
            else:
                out.append(e)
        return out

    def _resolve_next_events(self, effects: list) -> list:
        """NextEvents expand AFTER the current effects, mirroring
        gen_statem semantics: send effects are executed immediately
        during handle_effects while {next_event,..} actions are deferred
        to after the callback (ra_server_proc.erl:1317+).  Expanding
        inline instead would reorder the message stream — e.g. a
        commit-update AER built before a machine-appended follow-up
        would reach followers AFTER the follow-up's AER, and its stale
        prev index would look like a leader-log truncation."""
        out: list = []
        nexts: list = []
        for e in effects:
            if isinstance(e, NextEvent):
                nexts.append(e)
            else:
                out.append(e)
        for e in nexts:
            out.extend(self.handle(e.event))
        return out

    def _dispatch(self, event: Any) -> list:
        # generic non-leader fallback for client events carrying a reply
        # slot: every non-leader state (follower, candidate, pre_vote,
        # await_condition, receive_snapshot) answers not_leader immediately
        # instead of leaving the caller to time out
        if (self.raft_state != RaftState.LEADER and
                isinstance(event, (CommandEvent, ConsistentQueryEvent)) and
                event.from_ is not None and
                not (self.raft_state == RaftState.AWAIT_CONDITION and
                     self.condition is not None and
                     self.condition.transition_to == RaftState.LEADER)):
            # a parked leader (wal_down / transfer) postpones client events
            # instead of bouncing them — on resume they replay in the
            # leader state (ra_server_proc.erl:946-1010)
            return [Reply(event.from_,
                          ErrorResult("not_leader", self.leader_id))]
        if self.raft_state in (RaftState.STOP,
                               RaftState.DELETE_AND_TERMINATE):
            return []  # terminal: the shell tears this server down
        if isinstance(event, ForceMemberChangeEvent):
            # disaster-recovery escape hatch: shrink membership to self
            # only, then self-elect via pre-vote (quorum of one)
            # (ra_server.erl:830-831, :943-944, :1023-1024, :1320-1328).
            if self.raft_state == RaftState.AWAIT_CONDITION:
                # refused while parked — the reference's await_condition
                # state has no force_member_change clause (unsupported
                # call).  Exiting here would race the parked condition:
                # under a wal_down park the forced cluster-change append
                # itself fails mid-mutation (memtable advanced, cluster
                # not), and the postponed client backlog would be lost.
                if event.from_ is not None:
                    return [Reply(event.from_,
                                  ErrorResult("unsupported_call",
                                              self.leader_id))]
                return []
            if self.raft_state == RaftState.RECEIVE_SNAPSHOT:
                # a partial accept stream must not leak (the state's
                # normal exit teardown)
                self.log.abort_accept()
                self._accepting_snapshot = None
            effects = []
            if self.raft_state == RaftState.LEADER:
                # the reference re-dispatches through leader->follower
                # (ra_server.erl:830-831) so leader-only bookkeeping is
                # dropped before the shrink; do the teardown explicitly —
                # no snapshot-send token or waiting consistent query may
                # survive into the single-member configuration
                effects.extend(self._leader_teardown())
            effects.extend(self._append_cluster_change(
                {self.id: (Membership.VOTER, 0)}, None, None, []))
            if event.from_ is not None:
                effects.append(Reply(event.from_, "ok"))
            effects.extend(self._call_for_election_pre_vote())
            return effects
        if isinstance(event, AuxCommandEvent):
            return self.handle_aux("cmd", event.cmd, event.from_)
        handler = {
            RaftState.LEADER: self._handle_leader,
            RaftState.FOLLOWER: self._handle_follower,
            RaftState.CANDIDATE: self._handle_candidate,
            RaftState.PRE_VOTE: self._handle_pre_vote,
            RaftState.AWAIT_CONDITION: self._handle_await_condition,
            RaftState.RECEIVE_SNAPSHOT: self._handle_receive_snapshot,
        }[self.raft_state]
        return handler(event)

    # ------------------------------------------------------------------
    # helpers
    # ------------------------------------------------------------------

    def _update_term(self, term: int) -> None:
        if term > self.current_term:
            self.current_term = term
            self.voted_for = None
            self.stats["term_and_voted_for_updates"] += 1
            self.log.store_meta(current_term=term, voted_for=None)

    def _update_term_and_voted_for(self, term: int,
                                   voted_for: Optional[ServerId]) -> None:
        if term != self.current_term or voted_for != self.voted_for:
            self.current_term = term
            self.voted_for = voted_for
            self.stats["term_and_voted_for_updates"] += 1
            self.log.store_meta(current_term=term, voted_for=voted_for)

    def _applied_idx_term(self) -> IdxTerm:
        """(last_applied, its term) — the validated progress marker used
        by rewind/refusal replies.  fetch_term covers the snapshot index
        itself; anything below it is 0 (never sent in practice: applied
        never trails the snapshot)."""
        la = self.last_applied
        return IdxTerm(la, self.log.fetch_term(la) or 0)

    def last_idx_term(self) -> IdxTerm:
        """Effective last idx/term: log tail or snapshot (last_idx_term)."""
        lit = self.log.last_index_term()
        snap = self.log.snapshot_index_term()
        if snap.index > lit.index:
            return snap
        return lit

    def _peer_ids(self) -> list:
        return [pid for pid in self.cluster if pid != self.id]

    def _voter_count(self) -> int:
        return sum(1 for p in self.cluster.values()
                   if p.membership == Membership.VOTER)

    def required_quorum(self) -> int:
        return self._voter_count() // 2 + 1

    def _get_membership(self) -> Membership:
        peer = self.cluster.get(self.id)
        if peer is not None:
            return peer.membership
        if self.cluster_index_term.index == 0:
            # not in our own view and NO cluster change ever seen: a
            # freshly-started member whose '$ra_join' has not reached
            # it.  Voter-ness comes from the server CONFIG
            # (ra_server.erl:349-350 falls back to the config
            # membership) — without this a joined voter with an empty
            # log ignores vote requests and can veto elections forever
            # (found by the membership fuzz).
            return self.cfg.membership
        # absent from a view SHAPED BY a cluster change: we were
        # removed.  The config fallback must not apply — a removed
        # server that considered itself a voter would self-elect
        # against a quorum computed over a config that excludes it
        # (also found by the membership fuzz: a one-peer view makes
        # required_quorum 1, so the stale self-vote alone would seat a
        # bogus leader)
        return Membership.UNKNOWN

    def _set_cluster(self, new_cluster: dict[ServerId, Peer]) -> None:
        # preserve replication state of peers we already track
        for sid, peer in new_cluster.items():
            if sid in self.cluster:
                old = self.cluster[sid]
                peer.next_index = old.next_index
                peer.match_index = old.match_index
                peer.commit_index_sent = old.commit_index_sent
                peer.query_index = old.query_index
                peer.status = old.status
        self.cluster = new_cluster
        self.membership = self._get_membership()

    def is_voter(self) -> bool:
        return self.membership == Membership.VOTER

    def _aer_reply(self, term: int, success: bool) -> AppendEntriesReply:
        """Reply uses last *written* for match info but unwritten last index
        for next_index (ra_server.erl:2927-2939)."""
        lw = self.log.last_written()
        snap = self.log.snapshot_index_term()
        if snap.index > lw.index:
            lw = snap
        last_idx = self.last_idx_term().index
        return AppendEntriesReply(term=term, success=success,
                                  next_index=last_idx + 1,
                                  last_index=lw.index, last_term=lw.term,
                                  from_=self.id)

    def _heartbeat_reply(self) -> HeartbeatReply:
        return HeartbeatReply(query_index=self.query_index,
                              term=self.current_term, from_=self.id)

    # ------------------------------------------------------------------
    # elections (ra_server.erl:2211-2330)
    # ------------------------------------------------------------------

    def _call_for_election_pre_vote(self) -> list:
        # token must survive serialization (compared by value, not
        # identity — it crosses the wire on TCP transports)
        import uuid as _uuid
        self.pre_vote_token = _uuid.uuid4().hex
        last = self.last_idx_term()
        reqs = tuple(
            (pid, PreVoteRpc(term=self.current_term, token=self.pre_vote_token,
                             candidate_id=self.id, version=RA_PROTO_VERSION,
                             machine_version=self.machine_version,
                             last_log_index=last.index,
                             last_log_term=last.term))
            for pid in self._peer_ids())
        self._update_term_and_voted_for(self.current_term, self.id)
        self.leader_id = None
        self.votes = 0
        self.raft_state = RaftState.PRE_VOTE
        self_vote = PreVoteResult(term=self.current_term,
                                  token=self.pre_vote_token,
                                  vote_granted=True, from_=self.id)
        return [NextEvent(self_vote), SendVoteRequests(reqs),
                StartElectionTimeout("long")]

    def _call_for_election_candidate(self) -> list:
        new_term = self.current_term + 1
        last = self.last_idx_term()
        reqs = tuple(
            (pid, RequestVoteRpc(term=new_term, candidate_id=self.id,
                                 last_log_index=last.index,
                                 last_log_term=last.term))
            for pid in self._peer_ids())
        self._update_term_and_voted_for(new_term, self.id)
        self.leader_id = None
        self.votes = 0
        self.raft_state = RaftState.CANDIDATE
        self_vote = RequestVoteResult(term=new_term, vote_granted=True,
                                      from_=self.id)
        return [NextEvent(self_vote), SendVoteRequests(reqs),
                StartElectionTimeout("long")]

    def _process_pre_vote(self, rpc: PreVoteRpc) -> list:
        """Grant/deny a pre-vote without changing durable vote state
        beyond term adoption (ra_server.erl:2260-2319)."""
        if rpc.term < self.current_term:
            return [SendRpc(rpc.candidate_id,
                            PreVoteResult(term=self.current_term,
                                          token=rpc.token, vote_granted=False,
                                          from_=self.id))]
        self._update_term(rpc.term)
        last = self.last_idx_term()
        up_to_date = _log_up_to_date(rpc.last_log_index, rpc.last_log_term,
                                     last)
        if up_to_date and rpc.version > RA_PROTO_VERSION:
            granted = False
        elif up_to_date and (
                rpc.machine_version == self.effective_machine_version or
                (rpc.machine_version >= self.effective_machine_version and
                 rpc.machine_version <= self.machine_version)):
            self.voted_for = rpc.candidate_id
            granted = True
        else:
            granted = False
        effects: list = []
        if granted or self.raft_state != RaftState.FOLLOWER:
            effects.append(SendRpc(rpc.candidate_id,
                                   PreVoteResult(term=rpc.term,
                                                 token=rpc.token,
                                                 vote_granted=granted,
                                                 from_=self.id)))
        if not granted and self.raft_state == RaftState.FOLLOWER:
            effects.append(StartElectionTimeout("medium"))
        return effects

    def _process_request_vote(self, rpc: RequestVoteRpc) -> list:
        """Follower-side vote granting (ra_server.erl:1211-1251)."""
        if rpc.term < self.current_term:
            return [SendRpc(rpc.candidate_id,
                            RequestVoteResult(term=self.current_term,
                                              vote_granted=False,
                                              from_=self.id))]
        if (rpc.term == self.current_term and self.voted_for is not None
                and self.voted_for != rpc.candidate_id):
            return [SendRpc(rpc.candidate_id,
                            RequestVoteResult(term=rpc.term,
                                              vote_granted=False,
                                              from_=self.id))]
        self._update_term(rpc.term)
        last = self.last_idx_term()
        if _log_up_to_date(rpc.last_log_index, rpc.last_log_term, last):
            self._update_term_and_voted_for(rpc.term, rpc.candidate_id)
            return [SendRpc(rpc.candidate_id,
                            RequestVoteResult(term=rpc.term,
                                              vote_granted=True,
                                              from_=self.id)),
                    StartElectionTimeout("long")]
        return [SendRpc(rpc.candidate_id,
                        RequestVoteResult(term=rpc.term, vote_granted=False,
                                          from_=self.id))]

    def _leader_teardown(self) -> list:
        """Abandon leader-only bookkeeping on an involuntary step-down.

        Waiting/pending consistent queries are answered not_leader — the
        reference parks them and redirects once a new leader is known
        (process_new_leader_queries, ra_server.erl:1500-1510); with no
        successor known at teardown time not_leader is the honest reply
        and clients re-resolve.  In-flight snapshot-send tokens are
        invalidated so a late SnapshotSenderDone from a dead leadership
        cannot flip a peer back to NORMAL under a different regime."""
        effects: list = []
        for _qidx, from_, _fun, _ci in self.queries_waiting_heartbeats:
            if from_ is not None:
                effects.append(Reply(from_,
                                     ErrorResult("not_leader", None)))
        for from_, _fun, _ci in self.pending_consistent_queries:
            if from_ is not None:
                effects.append(Reply(from_,
                                     ErrorResult("not_leader", None)))
        self.queries_waiting_heartbeats = []
        self.pending_consistent_queries = []
        for peer in self.cluster.values():
            peer.snapshot_sender = None
            if peer.status == PeerStatus.SENDING_SNAPSHOT:
                peer.status = PeerStatus.NORMAL
        self.votes = 0
        return effects

    def _become_follower(self, term: int,
                         next_event: Any = None) -> list:
        # an actual LEADER stepping down (higher-term RPC/reply) drops
        # its leader-only bookkeeping here — the one choke point every
        # involuntary step-down goes through
        pre = (self._leader_teardown()
               if self.raft_state == RaftState.LEADER else [])
        self._update_term(term)
        self.leader_id = None
        self.votes = 0
        self.raft_state = RaftState.FOLLOWER
        effects: list = pre + [StartElectionTimeout("medium")]
        if next_event is not None:
            effects.insert(0, NextEvent(next_event))
        return effects

    def _become_leader(self) -> list:
        """Candidate won: initialise peers, establish leadership, append the
        noop for this term (ra_server.erl:845-859)."""
        self.leader_id = self.id
        self.raft_state = RaftState.LEADER
        self.votes = 0
        last_idx = self.last_idx_term().index
        for pid, peer in self.cluster.items():
            peer.next_index = last_idx + 1
            peer.match_index = 0
            peer.commit_index_sent = 0
            peer.query_index = 0
            if peer.status != PeerStatus.SENDING_SNAPSHOT:
                peer.status = PeerStatus.NORMAL
        self.cluster_change_permitted = False
        effects = self._make_all_rpcs()
        noop = NoopCommand(machine_version=self.machine_version)
        effects.append(NextEvent(CommandEvent(noop)))
        effects.append(RecordLeader(self.cfg.cluster_name, self.id,
                                    tuple(self.cluster)))
        effects.append(CancelElectionTimeout())
        # machine state_enter(leader) — re-establishes machine monitors
        # after failover (ra_server_proc state_enter effects; ra_machine
        # state_enter/2)
        effects.extend(self.effective_machine.state_enter(
            "leader", self.machine_state) or [])
        return effects

    # ------------------------------------------------------------------
    # follower (ra_server.erl:1032-1330)
    # ------------------------------------------------------------------

    def _handle_follower(self, event: Any) -> list:
        if isinstance(event, AppendEntriesRpc):
            return self._follower_aer(event)
        if isinstance(event, HeartbeatRpc):
            if event.term >= self.current_term:
                self._update_term(event.term)
                self.leader_id = event.leader_id
                self.query_index = max(self.query_index, event.query_index)
                return [SendRpc(event.leader_id, self._heartbeat_reply()),
                        StartElectionTimeout("medium")]
            return [SendRpc(event.leader_id, self._heartbeat_reply())]
        if isinstance(event, WrittenEvent):
            self.log.handle_written(event)
            effects: list = []
            # replicate-then-confirm: reply to the leader once our WAL
            # confirms (ra_server.erl:1183-1192).  NB: the commit index
            # is NOT evaluated here — commit_index is optimistically set
            # to leader_commit BEFORE the AER consistency check (both
            # here and in the reference, :1047-1048), so it may cover a
            # stale uncommitted suffix of a previous term that a failed
            # check left in place.  Applying is only safe from the AER
            # entry_ok path, where the prefix up to the leader's tail
            # has been validated (or reset) — exactly the reference's
            # shape, whose follower written-event clause only replies.
            if self.leader_id is not None:
                effects.append(SendRpc(self.leader_id,
                                       self._aer_reply(self.current_term,
                                                       True)))
            return effects
        if isinstance(event, PreVoteRpc):
            # DESIGN DIVERGENCE from the reference: every server grants
            # (pre-)votes based on term/votedFor/log alone — canonical
            # Raft.  The reference gates granting on the granter's OWN
            # membership (ra_server.erl:1197-1210), but a granter's
            # self-view can be arbitrarily stale in BOTH directions
            # (promoted-but-unaware, joined-but-uncaught-up), and the
            # fuzzers showed each one deadlocking elections that need
            # that vote.  Safety lives on the COUNTING side instead
            # (_count_grant: a candidate tallies only voters of its own
            # configuration), which the reference lacks.  Membership
            # still gates STANDING for election (the timeout below).
            return self._process_pre_vote(event)
        if isinstance(event, RequestVoteRpc):
            return self._process_request_vote(event)
        if isinstance(event, InstallSnapshotRpc):
            return self._follower_install_snapshot(event)
        if isinstance(event, (AppendEntriesReply, HeartbeatReply)):
            self._update_term(event.term)
            return []
        if isinstance(event, (RequestVoteResult, PreVoteResult)):
            return []
        if isinstance(event, ElectionTimeout):
            if not (self.is_voter() or self._removed_but_uncommitted()):
                return []
            return self._call_for_election_pre_vote()
        if isinstance(event, ForceElectionEvent):
            return self._call_for_election_candidate()
        if isinstance(event, TransferLeadershipEvent):
            # try_become_leader arrives at the transfer target as this event
            return self._call_for_election_pre_vote()
        if isinstance(event, CommandsEvent):
            # relay pipelined batches to the leader (the reference's
            # follower cast-forwarding, ra_server_proc.erl:822-849)
            if self.leader_id is not None and self.leader_id != self.id:
                return [SendRpc(self.leader_id, event)]
            return []
        if isinstance(event, CommandEvent) and event.from_ is None:
            if self.leader_id is not None and self.leader_id != self.id:
                return [SendRpc(self.leader_id, event)]
            return []
        if isinstance(event, (CommandEvent, ConsistentQueryEvent)):
            return []  # from_-carrying events answered by _dispatch fallback
        if isinstance(event, NodeEvent):
            # failure-detector verdict on the leader's node: arm an election
            # (the aten-driven path, ra_server_proc.erl:790-810)
            if (event.status == "down" and self.leader_id is not None
                    and event.node == self.leader_id.node
                    and self.is_voter()):
                return [StartElectionTimeout("short")]
            return []
        if isinstance(event, DownEvent):
            if (self.leader_id is not None and event.target == self.leader_id
                    and self.is_voter()):
                return [StartElectionTimeout("really_short")]
            return []
        if isinstance(event, TickEvent):
            return self._tick()
        return []

    def _follower_aer(self, rpc: AppendEntriesRpc) -> list:
        if rpc.term < self.current_term:
            return [SendRpc(rpc.leader_id,
                            self._aer_reply(self.current_term, False))]
        # valid leader for this term (ra_server.erl:1032-1156)
        effects: list = [StartElectionTimeout("medium")]
        self._update_term(rpc.term)
        self.leader_id = rpc.leader_id
        self.commit_index = max(self.commit_index, rpc.leader_commit)
        check = self._has_log_entry_or_snapshot(rpc.prev_log_index,
                                                rpc.prev_log_term)
        if check == "ok":
            entries = list(rpc.entries)
            payloads = rpc.payloads
            dropped = self._count_existing(entries)
            if dropped:
                entries = entries[dropped:]
                if payloads is not None:
                    payloads = payloads[dropped:]
            if not entries:
                last_idx = self.log.last_index_term().index
                if not rpc.entries and last_idx > rpc.prev_log_index:
                    # leader's log is shorter: reset ours to match
                    # (ra_server.erl:1056-1066) — but NEVER below our
                    # APPLIED index: applied entries are immutable, and
                    # a stale/pipelined empty AER can carry a prev point
                    # under them (found by the snapshot fuzz: the
                    # unclamped reset left applied > tail, wedging the
                    # member in an install-refusal livelock).  NB the
                    # clamp bound is last_applied, NOT commit_index —
                    # commit_index is adopted optimistically before the
                    # consistency check, so clamping there could retain
                    # (and then apply) never-validated stale entries in
                    # (prev, commit]; unapplied entries are always safe
                    # to truncate and re-receive.
                    new_tail = max(rpc.prev_log_index, self.last_applied)
                    self.log.set_last_index(new_tail)
                    # the reset may have truncated the entry whose
                    # cluster change this server adopted — revert NOW,
                    # not at the next append: a truncated server can
                    # win an election first and overwrite the change's
                    # index with its own noop, freezing a phantom
                    # configuration (soak seed 161122: the final leader
                    # held a config whose change entry no log carried)
                    self._revert_config_below(new_tail)
                effects.extend(self._evaluate_commit_index_follower())
                effects.append(SendRpc(rpc.leader_id,
                                       self._aer_reply(rpc.term, True)))
                return effects
            self._adopt_cluster_changes(entries)
            # the frame's pre-encoded durable images (when shipped)
            # ride into the log so the batch write skips re-encoding
            # (one WAL fan-in submit either way, ISSUE 13)
            if payloads is not None:
                self.log.write(entries, payloads)
            else:
                self.log.write(entries)
            effects.extend(self._evaluate_commit_index_follower())
            # success reply is sent when the WrittenEvent arrives
            return effects
        if check == "missing":
            # gap: ask leader to resend from our next index and hold in
            # await_condition for the entries to arrive out of order
            # (ra_server.erl:1118-1133)
            reply_eff = SendRpc(rpc.leader_id,
                                self._aer_reply(rpc.term, False))
            self.condition = Condition(
                predicate=_follower_catchup_predicate,
                transition_to=RaftState.FOLLOWER,
                timeout_ms=self.cfg.await_condition_timeout_ms,
                timeout_effects=[reply_eff])
            self.raft_state = RaftState.AWAIT_CONDITION
            effects.append(reply_eff)
            return effects
        # term mismatch: rewind to last_applied (ra_server.erl:1134-1156)
        la, la_term = self._applied_idx_term()
        reply = AppendEntriesReply(term=rpc.term, success=False,
                                   next_index=la + 1, last_index=la,
                                   last_term=la_term, from_=self.id)
        reply_eff = SendRpc(rpc.leader_id, reply)
        self.condition = Condition(
            predicate=_follower_catchup_predicate,
            transition_to=RaftState.FOLLOWER,
            timeout_ms=self.cfg.await_condition_timeout_ms,
            timeout_effects=[reply_eff])
        self.raft_state = RaftState.AWAIT_CONDITION
        effects.append(reply_eff)
        return effects

    def _has_log_entry_or_snapshot(self, idx: int, term: int) -> str:
        if idx == 0:
            return "ok"
        t = self.log.fetch_term(idx)
        if t is None:
            snap = self.log.snapshot_index_term()
            if snap.index == idx and snap.term == term:
                return "ok"
            return "missing"
        return "ok" if t == term else "term_mismatch"

    def _count_existing(self, entries: list) -> int:
        """How many leading entries are already present with the same
        idx+term (the drop_existing prefix length — returned as a count
        so the AER path can slice the shipped payloads in step)."""
        i = 0
        while i < len(entries) and self.log.exists(entries[i].index,
                                                   entries[i].term):
            i += 1
        return i

    def _adopt_cluster_changes(self, entries: list) -> None:
        """Followers adopt cluster changes when the entry is ADDED to
        the log, not when it applies (pre_append_log_follower,
        ra_server.erl:2865-2889): membership must be current for
        elections even while the apply frontier lags — e.g. the sole
        surviving member after the leader's own removal commits must
        know the new cluster to elect itself.

        ``entries`` is the post-drop_existing batch the caller is about
        to write, so every entry genuinely lands (new or conflicting).
        A batch starting at or below the recorded change index
        overwrites/TRUNCATES that change, so the view reverts to the
        prior configuration first — regardless of what the batch itself
        carries — and only then adopts any change in the batch (with
        the reverted config as its ``previous``, never the deposed
        leader's phantom one).  cluster_index_term is updated BEFORE
        _set_cluster so the cached membership (whose config fallback
        keys on cit==0) is computed against the new index."""
        if not entries:
            return
        self._revert_config_below(entries[0].index - 1)
        for e in entries:
            if isinstance(e.command, ClusterChangeCommand):
                self.previous_cluster = (
                    self.cluster_index_term,
                    tuple((sid, p.membership)
                          for sid, p in self.cluster.items()))
                self.cluster_index_term = IdxTerm(e.index, e.term)
                self._set_cluster(
                    dict_from_cluster_spec(e.command.cluster))

    def _revert_config_below(self, surviving_tail: int) -> None:
        """The log above ``surviving_tail`` is being discarded (an
        overwriting append batch, or the empty-AER shorter-log reset):
        if the adopted cluster change sat above it, the effective
        configuration must revert to what the surviving prefix says.
        previous_cluster covers the common one-change-rewind; when BOTH
        recorded changes are truncated, rescan the surviving prefix
        (newest change wins, snapshot meta as the base — the same
        resolution order as _cluster_spec_at)."""
        if self.cluster_index_term.index <= surviving_tail:
            return
        if self.previous_cluster is not None and \
                self.previous_cluster[0].index <= surviving_tail:
            prev_it, prev_spec = self.previous_cluster
            self.previous_cluster = None
            self.cluster_index_term = prev_it
            self._set_cluster(dict_from_cluster_spec(prev_spec))
            return
        self.previous_cluster = None
        for i in range(surviving_tail, self.log.first_index() - 1, -1):
            e = self.log.fetch(i)
            if e is not None and isinstance(e.command,
                                            ClusterChangeCommand):
                self.cluster_index_term = IdxTerm(e.index, e.term)
                self._set_cluster(
                    dict_from_cluster_spec(tuple(e.command.cluster)))
                return
        meta = self.log.snapshot_meta()
        if meta is not None:
            self.cluster_index_term = IdxTerm(meta.index, meta.term)
            self._set_cluster(dict_from_cluster_spec(tuple(meta.cluster)))
            return
        # no surviving change and no snapshot: back to the bootstrap
        # configuration (cit (0,0) keys the fresh-member config fallback,
        # same as init) — leaving the truncated view in force would keep
        # a phantom membership no log carries
        self.cluster_index_term = IdxTerm(0, 0)
        self._set_cluster({sid: Peer() for sid in self.cfg.initial_members})

    def _evaluate_commit_index_follower(self) -> list:
        """Apply up to min(last_index, commit_index) — may apply entries not
        yet fsynced locally; safe per the argument in
        ra_server.erl:1780-1813."""
        if self.leader_id is None:
            return []
        last_idx = self.log.last_index_term().index
        apply_to = min(last_idx, self.commit_index)
        effects: list = []
        self._apply_to(apply_to, effects)
        return _filter_follower_effects(effects)

    def _follower_install_snapshot(self, rpc: InstallSnapshotRpc) -> list:
        if rpc.term < self.current_term:
            return [SendRpc(rpc.leader_id,
                            InstallSnapshotResult(
                                term=self.current_term,
                                last_index=rpc.meta.index,
                                last_term=rpc.meta.term, from_=self.id,
                                token=rpc.token))]
        # restorative install: a member whose durable tail fell behind
        # its own applied index (e.g. a crash reverted the log while
        # meta.last_applied survived) must accept a snapshot AT its
        # applied index — refusing it as "stale" wedges the member
        # forever once the leader has compacted those entries
        restores_log = (rpc.meta.index >= self.last_applied and
                        rpc.meta.index >
                        self.log.last_index_term().index)
        if (rpc.chunk_number == 1
                and (rpc.meta.index > self.last_applied or restores_log)
                and self.machine_version >= rpc.meta.machine_version):
            self._update_term(rpc.term)
            self.leader_id = rpc.leader_id
            self._accepting_snapshot = rpc.meta
            self.log.begin_accept(rpc.meta)
            self.raft_state = RaftState.RECEIVE_SNAPSHOT
            return [NextEvent(rpc), StartElectionTimeout("medium")]
        # stale snapshot: confirm our progress so the leader can resume
        # replication.  The marker is the APPLIED frontier, not the raw
        # log tail: the tail may be a deposed leader's unvalidated
        # suffix, and advertising it sends the leader's repair below its
        # own snapshot — re-triggering the very install we just refused,
        # forever (found by the snapshot fuzz).  The applied point is
        # always validated state the leader can safely resume above.
        la, la_term = self._applied_idx_term()
        return [SendRpc(rpc.leader_id,
                        InstallSnapshotResult(term=self.current_term,
                                              last_index=la,
                                              last_term=la_term,
                                              from_=self.id,
                                              token=rpc.token))]

    # ------------------------------------------------------------------
    # receive_snapshot state (ra_server.erl:1333-1413)
    # ------------------------------------------------------------------

    def _handle_receive_snapshot(self, event: Any) -> list:
        if isinstance(event, InstallSnapshotRpc):
            if event.term < self.current_term:
                return []
            if event.chunk_number == 1 and \
                    event.meta != self._accepting_snapshot:
                # the leader restarted the transfer (e.g. it crashed and
                # a new leader owns a newer snapshot): begin again — the
                # partial stream is discarded (ra_snapshot.erl:465-508)
                self._accepting_snapshot = event.meta
                self.log.begin_accept(event.meta)
            meta = self._accepting_snapshot
            ok = self.log.accept_chunk(event.data, event.chunk_number,
                                       event.chunk_crc)
            if not ok:
                # corrupt chunk (or no stream): abort the install; our
                # unchanged progress report makes the leader restart
                self.log.abort_accept()
                self._accepting_snapshot = None
                self.raft_state = RaftState.FOLLOWER
                last = self.last_idx_term()
                return [SendRpc(event.leader_id,
                                InstallSnapshotResult(
                                    term=self.current_term,
                                    last_index=last.index,
                                    last_term=last.term, from_=self.id,
                                    token=event.token)),
                        StartElectionTimeout("medium")]
            if event.chunk_flag == "last":
                if not self.log.complete_accept():
                    self._accepting_snapshot = None
                    self.raft_state = RaftState.FOLLOWER
                    return [StartElectionTimeout("medium")]
                recovered = self.log.recover_snapshot_state()
                assert recovered is not None
                old_state = self.machine_state
                _, self.machine_state = recovered
                self.last_applied = meta.index
                self.commit_index = max(self.commit_index, meta.index)
                self.effective_machine_version = meta.machine_version
                self.effective_machine = self.machine.which_module(
                    meta.machine_version)
                # the installed config is as-of the snapshot point: the
                # change index MUST move with it, or it stays 0 and the
                # config-voter fallback re-arms — a server absent from
                # the installed cluster would then self-elect against a
                # quorum that excludes it (found by the combined fuzz)
                self.cluster_index_term = IdxTerm(meta.index, meta.term)
                self.previous_cluster = None
                self._set_cluster({sid: Peer(membership=m)
                                   for sid, m in meta.cluster})
                # the log RETAINS any consistent suffix above the
                # snapshot (install-at-applied-index restoration):
                # config changes in it are NEWER than the meta and must
                # stay in force — pinning only the meta silently
                # regressed this server's view to a config two changes
                # old, and it later elected itself under the stale
                # (larger) membership against a quorum the committed
                # chain had dissolved (soak seed 181279)
                retained = [
                    e for e in self.log.read_range(
                        meta.index + 1, self.log.last_index_term().index)
                    if isinstance(e.command, ClusterChangeCommand)]
                self._adopt_cluster_changes(retained)
                self._accepting_snapshot = None
                self.raft_state = RaftState.FOLLOWER
                effs = list(self.machine.snapshot_installed(
                    meta, self.machine_state, None, old_state))
                effs.append(SendRpc(event.leader_id,
                                    InstallSnapshotResult(
                                        term=self.current_term,
                                        last_index=meta.index,
                                        last_term=meta.term, from_=self.id,
                                        token=event.token)))
                effs.append(StartElectionTimeout("medium"))
                return effs
            return [SendRpc(event.leader_id,
                            InstallSnapshotResult(term=self.current_term,
                                                  last_index=meta.index,
                                                  last_term=meta.term,
                                                  from_=self.id,
                                                  token=event.token))]
        if isinstance(event, AppendEntriesRpc) and \
                event.term >= self.current_term:
            # a leader in a newer term interrupts the transfer
            self.log.abort_accept()
            self._accepting_snapshot = None
            self.raft_state = RaftState.FOLLOWER
            return [NextEvent(event)]
        if isinstance(event, ElectionTimeout):
            self.log.abort_accept()
            self._accepting_snapshot = None
            self.raft_state = RaftState.FOLLOWER
            return [StartElectionTimeout("medium")]
        if isinstance(event, WrittenEvent):
            self.log.handle_written(event)
            return []
        return []

    # ------------------------------------------------------------------
    # candidate (ra_server.erl:745-950)
    # ------------------------------------------------------------------

    def _count_grant(self, from_: Any) -> bool:
        """A grant counts toward quorum only when the granter is a VOTER
        of the candidate's OWN configuration (dissertation §4.2.2 vote
        tallying).  A fresh member's config-fallback voter-ness lets it
        grant before its cluster view catches up; an old-config
        candidate must not count such a grant against its (smaller)
        voter quorum — two leaders in one term otherwise (found by the
        membership fuzz).  The SELF-vote follows the same rule: a
        candidate absent from its own configuration (removed by an
        uncommitted change — see _removed_but_uncommitted) does not
        count itself; it needs a full quorum of the new config's
        voters.  Before any cluster change is known (bootstrap), the
        self-vote counts."""
        if from_ == self.id:
            peer = self.cluster.get(self.id)
            if peer is not None:
                return peer.membership == Membership.VOTER
            return self.cluster_index_term.index == 0
        peer = self.cluster.get(from_)
        return peer is not None and peer.membership == Membership.VOTER

    def _removed_but_uncommitted(self) -> bool:
        """Dissertation §4.2.2: a server absent from its own latest
        configuration keeps standing for election until the removing
        change COMMITS — it may still be needed, e.g. when it holds the
        longest log (containing that very change) and no new-config
        member can win without first obtaining it."""
        return (self.id not in self.cluster and
                self.cluster_index_term.index > self.commit_index)

    def _handle_candidate(self, event: Any) -> list:
        if isinstance(event, RequestVoteResult):
            if event.term > self.current_term:
                self._update_term_and_voted_for(event.term, None)
                return self._become_follower(event.term)
            if not event.vote_granted or event.term != self.current_term \
                    or not self._count_grant(event.from_):
                return []
            self.votes += 1
            if self.votes == self.required_quorum():
                return self._become_leader()
            return []
        if isinstance(event, AppendEntriesRpc):
            if event.term >= self.current_term:
                self._update_term_and_voted_for(event.term, None)
                return self._become_follower(event.term, next_event=event)
            return [SendRpc(event.leader_id,
                            self._aer_reply(self.current_term, False))]
        if isinstance(event, HeartbeatRpc):
            if event.term >= self.current_term:
                self._update_term_and_voted_for(event.term, None)
                return self._become_follower(event.term, next_event=event)
            return [SendRpc(event.leader_id, self._heartbeat_reply())]
        if isinstance(event, (AppendEntriesReply, HeartbeatReply)):
            if event.term > self.current_term:
                self._update_term_and_voted_for(event.term, None)
                return self._become_follower(event.term)
            return []
        if isinstance(event, RequestVoteRpc):
            if event.term > self.current_term:
                self._update_term_and_voted_for(event.term, None)
                eff = self._become_follower(event.term)
                return [NextEvent(event)] + eff
            return [SendRpc(event.candidate_id,
                            RequestVoteResult(term=self.current_term,
                                              vote_granted=False,
                                              from_=self.id))]
        if isinstance(event, PreVoteRpc):
            if event.term > self.current_term:
                self._update_term_and_voted_for(event.term, None)
                eff = self._become_follower(event.term)
                return [NextEvent(event)] + eff
            # candidate cannot simply reject (rabbitmq/ra#439)
            return self._process_pre_vote(event)
        if isinstance(event, InstallSnapshotRpc):
            if event.term >= self.current_term:
                return self._become_follower(event.term, next_event=event)
            # stale install chunk: refuse with OUR term (the follower
            # state's stale branch, :890-896) — found by the snapshot
            # soak (seeds 401146/401363/402692): a candidate that
            # dropped these silently left the deposed-but-unaware
            # leader retrying the install forever — with the peer in
            # SENDING_SNAPSHOT it gets no AER traffic either, so
            # nothing ever carried the higher term back
            last = self.last_idx_term()
            return [SendRpc(event.leader_id,
                            InstallSnapshotResult(
                                term=self.current_term,
                                last_index=last.index,
                                last_term=last.term, from_=self.id,
                                token=event.token))]
        if isinstance(event, PreVoteResult):
            return []
        if isinstance(event, ElectionTimeout):
            return self._call_for_election_candidate()
        if isinstance(event, WrittenEvent):
            self.log.handle_written(event)
            return []
        if isinstance(event, TickEvent):
            return self._tick()
        return []

    # ------------------------------------------------------------------
    # pre_vote (ra_server.erl:952-1030)
    # ------------------------------------------------------------------

    def _handle_pre_vote(self, event: Any) -> list:
        if isinstance(event, PreVoteResult):
            if event.term > self.current_term:
                return self._become_follower(event.term)
            if (event.vote_granted and event.token == self.pre_vote_token
                    and event.term == self.current_term
                    and self._count_grant(event.from_)):
                self.votes += 1
                if self.votes == self.required_quorum():
                    return self._call_for_election_candidate()
            return []
        if isinstance(event, (AppendEntriesRpc, HeartbeatRpc)):
            if event.term >= self.current_term:
                self._update_term(event.term)
                self.votes = 0
                self.raft_state = RaftState.FOLLOWER
                return [NextEvent(event)]
            if isinstance(event, HeartbeatRpc):
                return [SendRpc(event.leader_id, self._heartbeat_reply())]
            # stale AER: answer success=false with our term, exactly as
            # the follower state would — pre-vote never bumped the term,
            # so this is the deposed-leader path, not an election race
            return [SendRpc(event.leader_id,
                            self._aer_reply(self.current_term, False))]
        if isinstance(event, (AppendEntriesReply, HeartbeatReply)):
            if event.term > self.current_term:
                return self._become_follower(event.term)
            return []
        if isinstance(event, RequestVoteRpc):
            if event.term > self.current_term:
                eff = self._become_follower(event.term)
                return [NextEvent(event)] + eff
            return []
        if isinstance(event, InstallSnapshotRpc):
            if event.term >= self.current_term:
                self.votes = 0
                self.raft_state = RaftState.FOLLOWER
                return [NextEvent(event)]
            # stale install chunk: refuse with our term, exactly as the
            # follower state would (:890-896)
            last = self.last_idx_term()
            return [SendRpc(event.leader_id,
                            InstallSnapshotResult(
                                term=self.current_term,
                                last_index=last.index,
                                last_term=last.term, from_=self.id,
                                token=event.token))]
        if isinstance(event, PreVoteRpc):
            return self._process_pre_vote(event)
        if isinstance(event, RequestVoteResult):
            return []
        if isinstance(event, ElectionTimeout):
            return self._call_for_election_pre_vote()
        if isinstance(event, WrittenEvent):
            self.log.handle_written(event)
            return []
        if isinstance(event, TickEvent):
            return self._tick()
        return []

    # ------------------------------------------------------------------
    # leader (ra_server.erl:418-760)
    # ------------------------------------------------------------------

    def _handle_leader(self, event: Any) -> list:
        if isinstance(event, AppendEntriesReply):
            return self._leader_aer_reply(event)
        if isinstance(event, CommandEvent):
            return self._leader_command(event.command, event.from_)
        if isinstance(event, CommandsEvent):
            effects = self._leader_append_batch(event.commands,
                                                event.images)
            effects.extend(self._make_pipelined_rpcs())
            return effects
        if isinstance(event, WrittenEvent):
            self.log.handle_written(event)
            effects = self._evaluate_quorum()
            effects.extend(self._process_pending_consistent_queries())
            effects.extend(self._make_pipelined_rpcs())
            return effects
        if isinstance(event, InstallSnapshotResult):
            if event.term > self.current_term:
                self._update_term(event.term)
                self.leader_id = None
                return self._become_follower(event.term)
            peer = self.cluster.get(event.from_)
            if peer is None:
                return []
            if peer.snapshot_sender is not None and \
                    event.token != peer.snapshot_sender:
                # straggler result from an abandoned (timed-out)
                # transfer: must not regress the live transfer's state
                return []
            peer.status = PeerStatus.NORMAL
            peer.snapshot_sender = None
            # a REFUSED install reports the follower's own (possibly
            # stale) tail — verify it like an AER success confirm
            # before it may touch match (the combined fuzz found the
            # unchecked form looping forever: match poisoned beyond our
            # log -> prev unverifiable -> another snapshot send -> the
            # follower refuses again with the same stale tail)
            my_last = self.log.last_index_term().index
            verifiable = event.last_index >= self.log.first_index()
            if event.last_index > 0 and verifiable and \
                    self.log.fetch_term(event.last_index) != \
                    event.last_term:
                if event.last_index > my_last:
                    # stale surplus: only the empty-AER reset truncates
                    peer.next_index = my_last + 1
                    eff = self._make_rpc_for_peer(event.from_, peer, 1)
                    return [eff] if eff is not None else []
                peer.next_index = peer.match_index + 1
                return self._make_pipelined_rpcs()
            peer.match_index = max(peer.match_index, event.last_index)
            peer.commit_index_sent = event.last_index
            peer.next_index = event.last_index + 1
            return self._make_pipelined_rpcs()
        if isinstance(event, HeartbeatReply):
            if event.term > self.current_term:
                self._update_term(event.term)
                self.leader_id = None
                return self._become_follower(event.term)
            if event.term < self.current_term:
                return []
            return self._heartbeat_rpc_quorum(event.query_index, event.from_)
        if isinstance(event, ConsistentQueryEvent):
            return self._leader_consistent_query(event.from_, event.query_fn)
        if isinstance(event, RequestVoteRpc):
            if event.term > self.current_term:
                if event.candidate_id not in self.cluster:
                    return []
                self._update_term(event.term)
                self.leader_id = None
                return self._become_follower(event.term, next_event=event)
            return [SendRpc(event.candidate_id,
                            RequestVoteResult(term=self.current_term,
                                              vote_granted=False,
                                              from_=self.id))]
        if isinstance(event, PreVoteRpc):
            if event.term > self.current_term:
                if event.candidate_id not in self.cluster:
                    return []
                self._update_term(event.term)
                self.leader_id = None
                return self._become_follower(event.term, next_event=event)
            # enforce leadership (ra_server.erl:793-797)
            return self._make_all_rpcs()
        if isinstance(event, InstallSnapshotRpc):
            # higher term abdicates only for a KNOWN peer
            # (ra_server.erl:662-671); same/lower term is ignored — the
            # reference has no reply clause here and the suite pins it
            # (leader_receives_install_snapshot_rpc: "leader ignores
            # lower term"), unlike stale AERs which get a nack
            if event.term > self.current_term:
                if event.leader_id not in self.cluster:
                    return []
                self._update_term(event.term)
                self.leader_id = None
                return self._become_follower(event.term, next_event=event)
            return []
        if isinstance(event, (AppendEntriesRpc, HeartbeatRpc)):
            if event.term > self.current_term:
                self._update_term(event.term)
                self.leader_id = None
                return self._become_follower(event.term, next_event=event)
            if event.term == self.current_term:
                raise RuntimeError(
                    f"{self.id}: leader saw rpc in same term {event.term}")
            reply = (self._heartbeat_reply()
                     if isinstance(event, HeartbeatRpc)
                     else self._aer_reply(self.current_term, False))
            return [SendRpc(event.leader_id, reply)]
        if isinstance(event, (RequestVoteResult, PreVoteResult)):
            return []
        if isinstance(event, TransferLeadershipEvent):
            return self._leader_transfer(event)
        if isinstance(event, NodeEvent):
            # peer node status drives per-peer replication state
            # (handle_node_status, ra_server.erl:2107-2167)
            changed = False
            for pid, peer in self.cluster.items():
                if pid == self.id or pid.node != event.node:
                    continue
                if event.status == "down" and \
                        peer.status == PeerStatus.NORMAL:
                    peer.status = PeerStatus.DISCONNECTED
                elif event.status == "up" and \
                        peer.status == PeerStatus.DISCONNECTED:
                    peer.status = PeerStatus.NORMAL
                    changed = True
            return self._make_all_rpcs() if changed else []
        if isinstance(event, DownEvent):
            peer = self.cluster.get(event.target)
            if peer is not None and peer.status == PeerStatus.NORMAL:
                peer.status = PeerStatus.DISCONNECTED
            return []
        if isinstance(event, UpEvent):
            # a co-hosted sibling restarted: resume replication NOW —
            # without this edge a restarted follower behind the tail
            # can never catch up (it loses pre-votes and the leader
            # skips DISCONNECTED peers forever)
            peer = self.cluster.get(event.target)
            if peer is not None and \
                    peer.status == PeerStatus.DISCONNECTED:
                peer.status = PeerStatus.NORMAL
                eff = self._make_rpc_for_peer(event.target, peer, 1)
                return [eff] if eff is not None else []
            return []
        if isinstance(event, ElectionTimeout):
            return []
        if isinstance(event, TickEvent):
            return self._tick_leader()
        if isinstance(event, WalUpEvent):
            # resumed from wal_down: push fresh AERs so followers catch up
            # without waiting for the next tick
            return self._make_all_rpcs()
        return []

    def _leader_aer_reply(self, reply: AppendEntriesReply) -> list:
        peer = self.cluster.get(reply.from_)
        if peer is None:
            return []
        if reply.term > self.current_term:
            self._update_term(reply.term)
            self.leader_id = None
            return self._become_follower(reply.term)
        if reply.success and reply.term == self.current_term:
            if peer.status == PeerStatus.DISCONNECTED:
                peer.status = PeerStatus.NORMAL  # hearing from it = alive
            # the confirmed tail must be OUR entry before it can count
            # toward quorum: a follower that adopted this term while
            # still holding a stale suffix of a deposed leader confirms
            # (last_index, last_term) of that suffix via the written-
            # event reply path — advancing match on it would let a
            # divergent entry enter the commit median (the reference
            # checks reply terms only on the failure path,
            # ra_server.erl:477-532; the success path takes last_index
            # unchecked, :430-433 — this is deliberately stricter)
            my_last = self.log.last_index_term().index
            # indexes compacted behind our snapshot are unverifiable, not
            # divergent — trust them like the failure-path repair does
            # (a confirm at/below the snapshot index is always safe to
            # count: the snapshot itself covers it)
            verifiable = reply.last_index >= self.log.first_index()
            if reply.last_index > 0 and verifiable and \
                    self.log.fetch_term(reply.last_index) != reply.last_term:
                # stale-suffix success reply: never advance match on an
                # unverified tail.  Two repair shapes, both of which must
                # generate traffic or the exchange livelocks on repeated
                # identical confirms:
                if reply.last_index > my_last:
                    # follower's durable tail extends past our log (a
                    # deposed leader's surplus): only an EMPTY AER at our
                    # tail truncates it (the follower's reset branch —
                    # resent entries would just be duplicate-dropped)
                    peer.next_index = my_last + 1
                    eff = self._make_rpc_for_peer(reply.from_, peer, 1)
                    return [eff] if eff is not None else []
                # divergence within our range: rewind to the last
                # VERIFIED point; the resend overwrites the follower's
                # conflicting region (its write path truncates from the
                # first conflicting index)
                peer.next_index = peer.match_index + 1
                return self._make_pipelined_rpcs()
            peer.match_index = max(peer.match_index, reply.last_index)
            peer.next_index = max(peer.next_index, reply.next_index)
            effects = self._maybe_promote_peer(reply.from_)
            effects.extend(self._evaluate_quorum())
            effects.extend(self._process_pending_consistent_queries())
            effects.extend(self._make_pipelined_rpcs())
            # if we are no longer in the committed cluster, step down
            # (ra_server.erl:440-453)
            if (self.id not in self.cluster and
                    self.commit_index >= self.cluster_index_term.index):
                self.raft_state = RaftState.STOP
            return effects
        if reply.success:  # stale term reply
            return []
        # success=false: next_index repair (ra_server.erl:477-532)
        t = self.log.fetch_term(reply.last_index)
        if t is None:
            # DESIGN DIVERGENCE: the reference forwards match_index to
            # an UNVERIFIABLE point here (ra_server.erl:489-494).  A
            # refusal can advertise a deposed leader's surplus tail —
            # beyond our own log — and a poisoned match freezes commit
            # evaluation forever: agreed_commit lands on an index whose
            # term the leader cannot verify, so the §5.4.2 gate refuses
            # every subsequent commit (soak seed 181279: leader at tail
            # 36 held match=68 for its only voter, ci frozen while both
            # logs kept growing).  Same rule as the verified success
            # path: unverified points never advance replication state —
            # repair next_index only.
            my_last = self.log.last_index_term().index
            if reply.last_index > my_last:
                # surplus beyond our log: the empty-AER reset at our
                # tail truncates it (the follower's shorter-log branch).
                # Force the probe NOW like the sibling surplus repairs
                # (success path, install-result path): the pipelined
                # sender sees nothing new to send and would defer the
                # truncation to the next tick
                peer.next_index = my_last + 1
                eff = self._make_rpc_for_peer(reply.from_, peer, 1)
                return [eff] if eff is not None else []
            # at/below our snapshot floor: unverifiable here; the
            # snapshot-send path repairs such peers
            peer.next_index = max(reply.next_index, 1)
        elif t == reply.last_term and reply.last_index >= peer.match_index:
            peer.match_index = reply.last_index
            peer.next_index = reply.next_index
        elif reply.last_index < peer.match_index:
            peer.match_index = reply.last_index
            peer.next_index = reply.last_index + 1
        else:
            peer.next_index = max(min(peer.next_index - 1, reply.last_index),
                                  peer.match_index)
        return self._make_pipelined_rpcs()

    def _leader_command(self, cmd: Any, from_: Any) -> list:
        effects = self._leader_append(cmd, from_)
        effects.extend(self._make_pipelined_rpcs())
        return effects

    def _leader_append_batch(self, commands: tuple,
                             images: Optional[tuple] = None) -> list:
        """Drain one {commands, Batch} flush into the log as RUNS of
        plain user commands (ISSUE 13): one contiguous-index Entry run,
        ONE log batch-append (= one memtable lock cycle + one WAL
        fan-in submit) per run, with per-command bookkeeping reduced to
        the reply-mode/trace checks.  Anything that is not a plain
        UserCommand (membership ops, machine-internal commands) closes
        the run and takes the per-command append path — those are rare
        and carry their own effect logic.

        ``images`` (ISSUE 18) — codec payload images aligned with
        ``commands``, shipped by the wire receiver: the run's images
        ride into append_batch as the durable payloads, so a command
        that arrived over TCP is never re-encoded at the leader."""
        effects: list = []
        run: list = []
        run_imgs: Optional[list] = [] if images is not None else None
        append_batch = self._log_append_batch
        log = self.log

        def _flush_run() -> None:
            if not run:
                return
            idx0 = log.next_index()
            term = self.current_term
            entries = [Entry(idx0 + i, term, cmd)
                       for i, cmd in enumerate(run)]
            if append_batch is not None:
                append_batch(entries, run_imgs if run_imgs else None)
            else:
                for e in entries:
                    log.append(e)
            uid = self.cfg.uid
            for i, cmd in enumerate(run):
                if cmd.trace is not None:
                    # the trace ctx -> (uid, idx) join point (ISSUE 7)
                    record("cmd.append", trace=cmd.trace, uid=uid,
                           idx=idx0 + i, term=term, server=str(self.id))
                if cmd.reply_mode is ReplyMode.AFTER_LOG_APPEND and \
                        cmd.from_ is not None:
                    effects.append(Reply(cmd.from_,
                                         CommandResult(idx0 + i, term,
                                                       None, self.id)))
            run.clear()
            if run_imgs is not None:
                run_imgs.clear()

        for i, cmd in enumerate(commands):
            if type(cmd) is UserCommand:
                run.append(cmd)
                if run_imgs is not None:
                    run_imgs.append(images[i])
            else:
                _flush_run()
                effects.extend(self._leader_append(cmd, None))
        _flush_run()
        return effects

    def _leader_append(self, cmd: Any, from_: Any) -> list:
        """append_log_leader (ra_server.erl:2798-2915): join/leave commands
        become '$ra_cluster_change' appends; cluster changes are refused
        while one is in flight."""
        effects: list = []
        if isinstance(cmd, JoinCommand):
            if not self.cluster_change_permitted:
                return self._defer_or_refuse(cmd, from_, effects)
            if cmd.server_id in self.cluster:
                if from_ is not None:
                    effects.append(Reply(from_, ErrorResult("already_member",
                                                            self.id)))
                return effects
            new_cluster = {sid: (p.membership, p.promote_target)
                           for sid, p in self.cluster.items()}
            target = 0
            if cmd.membership == Membership.PROMOTABLE:
                target = self.log.next_index()
            new_cluster[cmd.server_id] = (cmd.membership, target)
            return self._append_cluster_change(new_cluster, cmd, from_,
                                               effects)
        if isinstance(cmd, LeaveCommand):
            if not self.cluster_change_permitted:
                return self._defer_or_refuse(cmd, from_, effects)
            if cmd.server_id not in self.cluster:
                if from_ is not None:
                    effects.append(Reply(from_, ErrorResult("not_member",
                                                            self.id)))
                return effects
            new_cluster = {sid: (p.membership, p.promote_target)
                           for sid, p in self.cluster.items()
                           if sid != cmd.server_id}
            if not any(ms == Membership.VOTER
                       for ms, _t in new_cluster.values()):
                # refusing is stricter than the reference but saves the
                # cluster: a voterless config is permanently dead — no
                # member can stand for election, so no later change can
                # ever repair it (found by the membership fuzz: leave of
                # the last voter while the rest were still promotable)
                if from_ is not None:
                    effects.append(Reply(from_, ErrorResult(
                        "last_voter", self.id)))
                return effects
            return self._append_cluster_change(new_cluster, cmd, from_,
                                               effects)
        # plain commands: attach from_ for the consensus reply
        if from_ is not None and hasattr(cmd, "from_"):
            cmd = replace(cmd, from_=from_)
        idx = self.log.next_index()
        entry = Entry(idx, self.current_term, cmd)
        self.log.append(entry)
        if getattr(cmd, "trace", None) is not None:
            # the trace ctx -> (uid, idx) join point: WAL/commit hop
            # events are idx-keyed, ra_trace stitches them through this
            record("cmd.append", trace=cmd.trace, uid=self.cfg.uid,
                   idx=idx, term=self.current_term, server=str(self.id))
        reply_mode = getattr(cmd, "reply_mode", None)
        if reply_mode == ReplyMode.AFTER_LOG_APPEND and from_ is not None:
            effects.append(Reply(from_, CommandResult(idx, self.current_term,
                                                      None, self.id)))
        return effects

    def _defer_or_refuse(self, cmd: Any, from_: Any, effects: list) -> list:
        if from_ is not None:
            effects.append(Reply(from_, ErrorResult(
                "cluster_change_not_permitted", self.id)))
        return effects

    def _append_cluster_change(self, cluster_spec: dict, cmd: Any,
                               from_: Any, effects: list) -> list:
        spec = tuple((sid, ms[0]) for sid, ms in cluster_spec.items())
        change = ClusterChangeCommand(
            cluster=spec, reply_mode=getattr(cmd, "reply_mode",
                                             ReplyMode.AWAIT_CONSENSUS),
            from_=from_)
        idx = self.log.next_index()
        prev = (self.cluster_index_term,
                tuple((sid, p.membership) for sid, p in self.cluster.items()))
        entry = Entry(idx, self.current_term, change)
        self.log.append(entry)
        # the new cluster takes effect immediately on append
        # (pre-commit, ra_server.erl append_cluster_change)
        new_cluster = {}
        for sid, (membership, target) in cluster_spec.items():
            peer = Peer(membership=membership, promote_target=target)
            new_cluster[sid] = peer
        self._set_cluster(new_cluster)
        self.cluster_change_permitted = False
        self.cluster_index_term = IdxTerm(idx, self.current_term)
        self.previous_cluster = prev
        return effects

    def _maybe_promote_peer(self, peer_id: ServerId) -> list:
        """Auto-promote a promotable non-voter that caught up
        (ra_server.erl:3218-3293)."""
        peer = self.cluster.get(peer_id)
        if (peer is None or peer.membership != Membership.PROMOTABLE or
                peer.match_index < peer.promote_target or
                not self.cluster_change_permitted):
            return []
        new_cluster = {sid: ((p.membership if sid != peer_id
                              else Membership.VOTER), p.promote_target)
                       for sid, p in self.cluster.items()}
        return self._append_cluster_change(
            new_cluster, JoinCommand(peer_id, reply_mode=ReplyMode.NOREPLY),
            None, [])

    # -- quorum arithmetic: THE kernel (ra_server.erl:2941-2993) ----------

    def match_indexes(self) -> list:
        """Voter match indexes; self is represented by last *written*
        (ra_server.erl:2977-2987) — but ONLY while self is a voter of
        the current configuration.  A leader removed by its own
        in-flight '$ra_leave' serves until the change commits
        (dissertation §4.2.2), and committing requires a majority of
        the NEW config: counting its own log in place of a new-config
        voter lets it "commit" entries a real quorum never held (found
        by the combined fuzz: the removed leader committed its own
        removal at an index one new-config voter was missing, wedging a
        follower with applied > tail).  The reference includes own
        unconditionally and shares the hazard."""
        lw = self.log.last_written()
        snap = self.log.snapshot_index_term()
        own = max(lw.index, snap.index)
        self_peer = self.cluster.get(self.id)
        idxs = []
        if self_peer is not None and \
                self_peer.membership == Membership.VOTER:
            idxs.append(own)
        for pid, peer in self.cluster.items():
            if pid == self.id:
                continue
            if peer.membership != Membership.VOTER:
                continue
            idxs.append(peer.match_index)
        # degenerate safety net: no voters visible (transient states) —
        # fall back to own so the median is defined
        return idxs or [own]

    @staticmethod
    def agreed_commit(indexes: list) -> int:
        """Quorum-agreed index: sort desc, take element trunc(n/2)+1 (1-based)
        (ra_server.erl:2989-2993).  This is the scalar oracle for the
        batched kernel in ra_tpu.ops.quorum."""
        s = sorted(indexes, reverse=True)
        return s[len(s) // 2]

    def _increment_commit_index(self) -> None:
        potential = self.agreed_commit(self.match_indexes())
        if potential <= self.commit_index:
            return
        # §5.4.2: only commit entries from the current term
        t = self.log.fetch_term(potential)
        if t == self.current_term:
            self.commit_index = potential
            # idx-keyed commit hop (one event per ADVANCE, not per
            # entry): ra_trace resolves a command's commit time as the
            # first advance at or past its append idx
            record("cmd.commit", uid=self.cfg.uid, idx=potential,
                   term=self.current_term)

    def _evaluate_quorum(self) -> list:
        ci0 = self.commit_index
        self._increment_commit_index()
        effects: list = []
        if self.commit_index > ci0:
            effects.append(AuxEffect("eval"))
        self._apply_to(self.commit_index, effects)
        return effects

    # -- the apply fold (ra_server.erl:2557-2744) -------------------------

    def _apply_to(self, apply_to: int, effects: list,
                  suppress: bool = False) -> None:
        if apply_to <= self.last_applied:
            return
        if self.machine_version < self.effective_machine_version:
            return
        last_idx = self.log.last_index_term().index
        to = min(last_idx, apply_to)
        notifys: dict = {}
        t0 = time.monotonic()
        entries = self.log.read_range(self.last_applied + 1, to)
        batch_fn = self.effective_machine.apply_batch
        # applied-notification routing is leader-only (followers drop
        # Notify effects in _filter_follower_effects) — skip collecting
        # what would be thrown away (ISSUE 13); from_-carrying replies
        # (member-replier await_consensus) are preserved regardless
        collect_notify = self.raft_state == RaftState.LEADER or \
            (self.raft_state == RaftState.AWAIT_CONDITION and
             self.condition is not None and
             self.condition.transition_to == RaftState.LEADER)
        i = 0
        n = len(entries)
        while i < n:
            if self.machine_version < self.effective_machine_version:
                break  # version gate: cannot apply further (same stop
                # condition _apply_one enforces per entry)
            entry = entries[i]
            if batch_fn is None or type(entry.command) is not UserCommand:
                self._apply_one(entry, effects, notifys, suppress)
                i += 1
                # the apply may have bumped the effective machine (a
                # noop version bump mid-range): re-resolve the batch fn
                batch_fn = self.effective_machine.apply_batch
                continue
            # batched fold (ISSUE 13): hand the machine the contiguous
            # same-term run of plain user commands in ONE call; replies
            # come back in order and feed the same notify plumbing
            j = i + 1
            term = entry.term
            while j < n and entries[j].term == term and \
                    type(entries[j].command) is UserCommand:
                j += 1
            run = entries[i:j]
            self._apply_user_run(run, batch_fn, effects, notifys,
                                 suppress, collect_notify)
            i = j
        self.commit_latency = time.monotonic() - t0
        if notifys and not suppress:
            for to_pid, corrs in notifys.items():
                effects.append(Notify(to_pid, tuple(corrs)))

    def _apply_user_run(self, run: list, batch_fn, effects: list,
                        notifys: dict, suppress: bool,
                        collect_notify: bool = True) -> None:
        """Apply one contiguous run of plain user commands through the
        machine's batched fold.  Exactly order-equivalent to folding
        apply() over the run (the apply_batch contract); the per-command
        tail work (trace hops, reply/notify routing) is reduced to the
        cheapest possible checks — and reply routing is skipped
        entirely for commands that cannot owe one (no from_, no
        notify_to), which on followers is every pipelined command."""
        first = run[0]
        meta = ApplyMeta(index=first.index, term=first.term,
                         machine_version=self.effective_machine_version)
        result = batch_fn(meta, [e.command.data for e in run],
                          self.machine_state)
        if len(result) == 3:
            self.machine_state, replies, app_effs = result
        else:
            self.machine_state, replies = result
            app_effs = []
        self.last_applied = run[-1].index
        if suppress:
            return  # recovery replay: not a live apply hop
        if app_effs:
            effects.extend(app_effs)
        uid = self.cfg.uid
        for e, reply in zip(run, replies):
            cmd = e.command
            if cmd.trace is not None:
                record("cmd.apply", trace=cmd.trace, uid=uid,
                       idx=e.index, server=str(self.id))
            if cmd.from_ is not None or \
                    (collect_notify and cmd.notify_to is not None):
                self._add_reply(cmd, e.index, e.term, reply, effects,
                                notifys)

    def _apply_one(self, entry: Entry, effects: list, notifys: dict,
                   suppress: bool) -> None:
        idx, term, cmd = entry
        if self.machine_version < self.effective_machine_version:
            return  # cannot apply further (version gate)
        if isinstance(cmd, UserCommand):
            meta = ApplyMeta(index=idx, term=term,
                             machine_version=self.effective_machine_version,
                             from_=cmd.from_, reply_mode=cmd.reply_mode)
            result = self.effective_machine.apply(meta, cmd.data,
                                                  self.machine_state)
            if len(result) == 3:
                self.machine_state, reply, app_effs = result
            else:
                self.machine_state, reply = result
                app_effs = []
            self.last_applied = idx
            if suppress:
                return  # recovery replay: not a live apply hop
            if cmd.trace is not None:
                record("cmd.apply", trace=cmd.trace, uid=self.cfg.uid,
                       idx=idx, server=str(self.id))
            effects.extend(app_effs)
            self._add_reply(cmd, idx, term, reply, effects, notifys)
            return
        if isinstance(cmd, NoopCommand):
            self._apply_noop(entry, cmd, effects, suppress)
            return
        if isinstance(cmd, ClusterChangeCommand):
            if (idx > self.cluster_index_term.index and
                    term >= self.cluster_index_term.term):
                # recovery path: actually apply the change (cit before
                # _set_cluster — the membership cache's config fallback
                # keys on cit==0)
                self.cluster_index_term = IdxTerm(idx, term)
                self._set_cluster(dict_from_cluster_spec(cmd.cluster))
            self.cluster_change_permitted = True
            self.last_applied = idx
            if not suppress:
                self._add_reply(cmd, idx, term, "ok", effects, notifys)
            return
        if isinstance(cmd, ClusterDeleteCommand):
            self.last_applied = idx
            self.raft_state = RaftState.DELETE_AND_TERMINATE
            if not suppress:
                self._add_reply(cmd, idx, term, "ok", effects, notifys)
                effects.extend(self.machine.state_enter("eol",
                                                        self.machine_state))
            return
        # unknown command: count as applied
        self.last_applied = idx

    def _apply_noop(self, entry: Entry, cmd: NoopCommand, effects: list,
                    suppress: bool) -> None:
        idx, term, _ = entry
        if term == self.current_term:
            self.cluster_change_permitted = True
        next_ver = cmd.machine_version
        if next_ver > self.effective_machine_version:
            if self.machine_version >= next_ver:
                old_ver = self.effective_machine_version
                self.effective_machine_version = next_ver
                self.machine_versions.insert(0, (idx, next_ver))
                self.effective_machine = self.machine.which_module(next_ver)
                # apply the version-bump as a pseudo user command
                # (ra_server.erl:2695-2712)
                meta = ApplyMeta(index=idx, term=term,
                                 machine_version=next_ver)
                result = self.effective_machine.apply(
                    meta, ("machine_version", old_ver, next_ver),
                    self.machine_state)
                self.machine_state = result[0]
                if len(result) == 3 and not suppress:
                    effects.extend(result[2])
                self.last_applied = idx
            else:
                # cannot understand the new version: stop applying
                self.effective_machine_version = next_ver
        else:
            self.last_applied = idx

    def _add_reply(self, cmd: Any, idx: int, term: int, reply: Any,
                   effects: list, notifys: dict) -> None:
        mode = getattr(cmd, "reply_mode", None)
        if mode == ReplyMode.AWAIT_CONSENSUS and \
                getattr(cmd, "from_", None) is not None:
            replier = getattr(cmd, "reply_from", None) or "leader"
            effects.append(Reply(cmd.from_,
                                 CommandResult(idx, term, reply, self.id),
                                 replier=replier))
        elif mode == ReplyMode.NOTIFY and \
                getattr(cmd, "notify_to", None) is not None:
            notifys.setdefault(cmd.notify_to, []).append(
                (cmd.correlation, reply))

    # -- replication rpcs (ra_server.erl:1862-2016) ------------------------

    def _make_pipelined_rpcs(self) -> list:
        """Per-peer pipelining with flow control: in-flight bounded by
        max_pipeline_count, batches by max_append_entries_batch."""
        effects: list = []
        next_log_idx = self.log.next_index()
        # one read memo per send wave: caught-up peers want the SAME
        # range, so the second peer's AER reuses the first's entries +
        # payloads instead of re-reading the log (ISSUE 13)
        memo: dict = {}
        for pid, peer in self.cluster.items():
            if pid == self.id or peer.status != PeerStatus.NORMAL:
                continue
            if not (peer.next_index < next_log_idx or
                    peer.commit_index_sent < self.commit_index):
                continue
            in_flight = peer.next_index - peer.match_index - 1
            if in_flight >= self.cfg.max_pipeline_count:
                continue
            batch = min(self.cfg.max_append_entries_batch,
                        self.cfg.max_pipeline_count - in_flight)
            eff = self._make_rpc_for_peer(pid, peer, batch, memo)
            if eff is not None:
                peer.commit_index_sent = self.commit_index
                effects.append(eff)
        return effects

    def _make_all_rpcs(self) -> list:
        """Empty/heartbeat AERs to all normal-status peers (make_all_rpcs)."""
        effects: list = []
        effects.extend(self._update_heartbeat_rpcs())
        for pid, peer in self.cluster.items():
            if pid == self.id or peer.status != PeerStatus.NORMAL:
                continue
            eff = self._make_rpc_for_peer(pid, peer, 1)
            if eff is not None:
                effects.append(eff)
        return effects

    def _make_rpc_for_peer(self, pid: ServerId, peer: Peer,
                           batch: int,
                           memo: Optional[dict] = None) -> Optional[Any]:
        prev_idx = peer.next_index - 1
        if prev_idx == 0 and self.log.snapshot_index_term().index > 0:
            # peer wants the log from the very start but our prefix is
            # compacted behind a snapshot: entries 1..snap are gone, so
            # prev=0 would ship a gapped batch (fetch_term(PrevIdx)
            # undefined ∧ PrevIdx < snapshot idx, ra_server.erl:1962-1981)
            peer.status = PeerStatus.SENDING_SNAPSHOT
            peer.snapshot_started = time.monotonic()
            peer.snapshot_sender = self._next_snapshot_token()
            return SendSnapshot(pid, (self.id, self.current_term),
                                token=peer.snapshot_sender)
        prev_term = self.log.fetch_term(prev_idx) if prev_idx > 0 else 0
        if prev_term is None:
            snap = self.log.snapshot_index_term()
            if snap.index == prev_idx:
                prev_term = snap.term
            else:
                # entry compacted away: peer needs a snapshot
                # (ra_server.erl:1962-1981)
                peer.status = PeerStatus.SENDING_SNAPSHOT
                peer.snapshot_started = time.monotonic()
                peer.snapshot_sender = self._next_snapshot_token()
                return SendSnapshot(pid, (self.id, self.current_term),
                                    token=peer.snapshot_sender)
        last_idx = self.log.last_index_term().index
        to = min(last_idx, prev_idx + batch)
        entries: tuple = ()
        payloads = None
        if to > prev_idx:
            # one-lock batched read WITH the already-encoded durable
            # images when the range is memtable-resident (the common
            # steady-state case) — bounded by the frame byte budget;
            # catch-up ranges that left the memtable fall back to the
            # plain read and followers re-encode (ISSUE 13)
            cached = memo.get((prev_idx + 1, to)) \
                if memo is not None else None
            if cached is not None:
                entries, payloads = cached
            else:
                got = self._log_read_payloads(
                    prev_idx + 1, to,
                    self.cfg.max_append_entries_bytes) \
                    if self._log_read_payloads is not None else None
                if got is not None:
                    entries = tuple(got[0])
                    payloads = tuple(got[1])
                else:
                    entries = tuple(self.log.read_range(prev_idx + 1,
                                                        to))
                    payloads = None
                if memo is not None:
                    memo[(prev_idx + 1, to)] = (entries, payloads)
            if entries:
                peer.next_index = entries[-1].index + 1
                n = len(entries)
                self.stats["aer_batches_sent"] += 1
                self.stats["aer_batch_entries"] += n
                self._aer_batch_sizes.append(n)
                # ONE event per replication batch (never per entry):
                # the wire-batching health signal (ISSUE 13 / RA06)
                record("rpc.batch", to=str(pid), n=n,
                       bytes=sum(map(len, payloads))
                       if payloads is not None else -1)
        return SendRpc(pid, AppendEntriesRpc(
            term=self.current_term, leader_id=self.id,
            prev_log_index=prev_idx, prev_log_term=prev_term or 0,
            leader_commit=self.commit_index, entries=entries,
            payloads=payloads))

    # -- consistent queries (ra_server.erl:3032-3190) ----------------------

    def _leader_consistent_query(self, from_: Any, query_fn: Any) -> list:
        if not self.cluster_change_permitted:
            # a new leader must commit its noop first (:3174-3190)
            self.pending_consistent_queries.append((from_, query_fn,
                                                    self.commit_index))
            return []
        return self._make_heartbeat_rpcs(from_, query_fn, self.commit_index)

    def _make_heartbeat_rpcs(self, from_: Any, query_fn: Any,
                             commit_index: int) -> list:
        self.query_index += 1
        self.queries_waiting_heartbeats.append(
            (self.query_index, from_, query_fn, commit_index))
        effects: list = []
        for pid, peer in self.cluster.items():
            if pid == self.id or peer.membership != Membership.VOTER:
                continue
            effects.append(SendRpc(pid, HeartbeatRpc(
                query_index=self.query_index, term=self.current_term,
                leader_id=self.id)))
        if self._voter_count() == 1:
            effects.extend(self._apply_ready_queries())
        return effects

    def _update_heartbeat_rpcs(self) -> list:
        if not self.queries_waiting_heartbeats:
            return []
        effects: list = []
        for pid, peer in self.cluster.items():
            if pid == self.id or peer.membership != Membership.VOTER:
                continue
            effects.append(SendRpc(pid, HeartbeatRpc(
                query_index=self.query_index, term=self.current_term,
                leader_id=self.id)))
        return effects

    def _heartbeat_rpc_quorum(self, reply_qidx: int,
                              from_peer: ServerId) -> list:
        peer = self.cluster.get(from_peer)
        if peer is None:
            return []
        peer.query_index = max(peer.query_index, reply_qidx)
        return self._apply_ready_queries()

    def _agreed_query_index(self) -> int:
        # same voter gate as match_indexes: a leader removed by its
        # in-flight change must not count its own confirmation toward
        # the new config's heartbeat quorum, or a linearizable read can
        # be certified by a minority of the real voters
        self_peer = self.cluster.get(self.id)
        idxs = []
        if self_peer is not None and \
                self_peer.membership == Membership.VOTER:
            idxs.append(self.query_index)
        for pid, peer in self.cluster.items():
            if pid == self.id or peer.membership != Membership.VOTER:
                continue
            idxs.append(peer.query_index)
        return self.agreed_commit(idxs or [self.query_index])

    def _apply_ready_queries(self) -> list:
        agreed = self._agreed_query_index()
        ready = [q for q in self.queries_waiting_heartbeats if q[0] <= agreed]
        if not ready:
            return []
        self.queries_waiting_heartbeats = [
            q for q in self.queries_waiting_heartbeats if q[0] > agreed]
        effects: list = []
        for _qidx, from_, query_fn, _ci in ready:
            result = query_fn(self.machine_state)
            effects.append(Reply(from_, CommandResult(
                self.last_applied, self.current_term, result, self.id)))
        return effects

    def _process_pending_consistent_queries(self) -> list:
        if not self.pending_consistent_queries or \
                not self.cluster_change_permitted:
            return []
        pending, self.pending_consistent_queries = \
            self.pending_consistent_queries, []
        effects: list = []
        for from_, query_fn, ci in pending:
            effects.extend(self._make_heartbeat_rpcs(from_, query_fn, ci))
        return effects

    # -- leader transfer (ra_server.erl:806-828) ---------------------------

    def _leader_transfer(self, event: TransferLeadershipEvent) -> list:
        target = event.target
        if target == self.id:
            if event.from_ is not None:
                return [Reply(event.from_, "already_leader")]
            return []
        if target not in self.cluster:
            if event.from_ is not None:
                return [Reply(event.from_,
                              ErrorResult("unknown_member", self.id))]
            return []
        self._transfer_target = target
        self.condition = Condition(
            predicate=_transfer_leadership_predicate,
            transition_to=RaftState.LEADER,
            timeout_ms=self.cfg.election_timeout_ms,
            timeout_effects=[])
        self.raft_state = RaftState.AWAIT_CONDITION
        effects: list = [SendRpc(target, TransferLeadershipEvent(target))]
        if event.from_ is not None:
            effects.append(Reply(event.from_, "ok"))
        return effects

    # -- await_condition (ra_server.erl:946-1010 in proc; core predicates) -

    def enter_wal_down(self) -> list:
        """A log write raised WalDown: park in await_condition until the
        supervisor restarts the WAL and the log surfaces a WalUpEvent,
        then resume the previous role.  The reference keeps the server
        alive through exactly this state while ra_log_wal is supervised
        back up (ra_server.erl:538-554); killing the server instead would
        convert a recoverable infra fault into data-plane loss.

        The failed entry is NOT lost: DurableLog._put records it in the
        memtable before submitting to the WAL, and wal_restarted() resends
        everything above last_written to the new incarnation."""
        if self.raft_state in (RaftState.STOP,
                               RaftState.DELETE_AND_TERMINATE,
                               RaftState.AWAIT_CONDITION):
            return []
        back = self.raft_state if self.raft_state in (
            RaftState.LEADER, RaftState.FOLLOWER) else RaftState.FOLLOWER
        self.condition = Condition(
            predicate=_wal_up_predicate,
            transition_to=back,
            timeout_ms=self.cfg.await_condition_timeout_ms,
            timeout_effects=[])
        self.raft_state = RaftState.AWAIT_CONDITION
        # bounded stay: if the supervisor never revives the WAL the
        # timeout path re-enters the previous role (whose next write
        # re-parks) rather than wedging here forever
        return [StartElectionTimeout("medium")]

    def _handle_await_condition(self, event: Any) -> list:
        if isinstance(event, ElectionTimeout):
            cond = self.condition
            if cond is not None and cond.predicate is _wal_up_predicate \
                    and not getattr(self.log, "wal_is_up",
                                    lambda: True)():
                # WAL still dead: bouncing out would re-park on the next
                # write and lose the postponed backlog mid-replay — stay
                # parked until the supervisor finishes (clients time out
                # on their own clocks, same as the reference's hold)
                return [StartElectionTimeout("medium")]
            # condition timed out
            self.condition = None
            self.raft_state = cond.transition_to if cond else \
                RaftState.FOLLOWER
            effs = list(cond.timeout_effects) if cond else []
            effs.append(StartElectionTimeout("medium"))
            effs.extend(self._replay_condition_pending())
            return effs
        if isinstance(event, RequestVoteRpc):
            # a vote request exits the wait: revert to follower and
            # re-dispatch it there (ra_server.erl:1453-1454).  Denying
            # while parked starves elections — e.g. after a leader's
            # self-removal commits, the survivors parked on its log gap
            # would veto every candidacy forever (found by the
            # membership fuzz).  A parked LEADER, however, applies the
            # same gates an active leader does (:1233-1243): a stale
            # same/lower-term request is denied in place and a
            # non-member candidate is ignored — otherwise a removed
            # node replaying an old candidacy would depose the parked
            # leader, erroring its waiting queries and aborting
            # snapshot sends an active leader would have kept.
            if self.condition is not None and \
                    self.condition.transition_to == RaftState.LEADER:
                if event.term <= self.current_term:
                    return [SendRpc(event.candidate_id,
                                    RequestVoteResult(
                                        term=self.current_term,
                                        vote_granted=False,
                                        from_=self.id))]
                if event.candidate_id not in self.cluster:
                    return []
                pre = self._leader_teardown()
            else:
                pre = []
            self.condition = None
            self.raft_state = RaftState.FOLLOWER
            return (pre + [NextEvent(event)] +
                    self._replay_condition_pending())
        if isinstance(event, PreVoteRpc):
            # a HIGHER-term pre-vote exits the wait like a vote request
            # does: a parked LEADER that merely adopted the term in
            # place would later resume as leader of a term it never won
            # (two leaders in one term)
            if event.term > self.current_term:
                if self.condition is not None and \
                        self.condition.transition_to == RaftState.LEADER:
                    if event.candidate_id not in self.cluster:
                        return []    # non-member: same gate as :1246
                    pre = self._leader_teardown()
                else:
                    pre = []
                self.condition = None
                self.raft_state = RaftState.FOLLOWER
                return (pre + [NextEvent(event)] +
                        self._replay_condition_pending())
            # same-term pre-votes are answered IN PLACE — granting one
            # does not exit the wait (ra_server.erl:1455-1456).  Like
            # the follower path, no granter-side membership gate: real
            # votes are equally permissive, so a pre-vote grant here
            # cannot lure a candidate into an election it then loses.
            return self._process_pre_vote(event)
        if isinstance(event, WrittenEvent):
            self.log.handle_written(event)
            if self.condition is not None and \
                    self.condition.transition_to == RaftState.LEADER:
                # a parked leader (wal_down/transfer) still counts its own
                # confirms toward quorum — otherwise a confirm consumed
                # here is never re-evaluated and committed-but-unacked
                # clients hang until the next unrelated write
                effs = self._evaluate_quorum()
                effs.extend(self._process_pending_consistent_queries())
                return effs
            if self.leader_id is not None and \
                    self.condition is not None and \
                    self.condition.transition_to == RaftState.FOLLOWER:
                return [SendRpc(self.leader_id,
                                self._aer_reply(self.current_term, True))]
            return []
        cond = self.condition
        if cond is not None and cond.predicate(event, self):
            self.condition = None
            self.raft_state = cond.transition_to
            # the satisfying event re-dispatches in the new state, then the
            # postponed backlog replays in arrival order (gen_statem's
            # postpone-retry on state change, ra_server_proc.erl:946-1010)
            return [NextEvent(event)] + self._replay_condition_pending()
        # An unsatisfying AER during the CATCH-UP park is dropped with a
        # refusal to ITS sender, not postponed (the reference's await
        # catch-all drops such messages, ra_server.erl:1766-1775).  Two
        # liveness holes otherwise (soak seed 140855, anchored
        # in-suite): postponed AERs re-park on replay AHEAD of fresh
        # traffic — each condition kick consumes exactly one stale head
        # while a live leader's heartbeats queue behind, a treadmill
        # that never drains — and the park-time refusal stays addressed
        # to the leader of the PARKING term, so a newer leader would
        # never learn this follower's position.  The refusal carries
        # the sender's term when current (term adoption itself waits
        # for an entry we can use) and our own term against a stale
        # sender, exactly as a live follower would answer.  Safe: a
        # refusal only resets the sender's next_index; AERs carry no
        # client state to lose and leaders resend.
        if isinstance(event, AppendEntriesRpc) and cond is not None and \
                cond.predicate is _follower_catchup_predicate:
            reply_term = max(event.term, self.current_term)
            return [SendRpc(event.leader_id,
                            self._aer_reply(reply_term, False))]
        # postpone: buffer the event for replay when the condition exits
        # (ra_server_proc postpones via gen_statem; dropping would force a
        # leader resend round-trip).  Periodic ticks are not worth keeping.
        if not isinstance(event, TickEvent):
            self.condition_pending.append(event)
            if len(self.condition_pending) > MAX_CONDITION_PENDING:
                evicted = self.condition_pending.popleft()
                frm = getattr(evicted, "from_", None)
                if frm is not None:
                    # an evicted client call must not hang silently —
                    # bounce it the way the pre-postpone path did
                    return [Reply(frm, ErrorResult("not_leader",
                                                   self.leader_id))]
        return []

    def _replay_condition_pending(self) -> list:
        """Drain events postponed during await_condition as NextEvents —
        they re-dispatch in the state the condition exited into."""
        pending = list(self.condition_pending)
        self.condition_pending.clear()
        return [NextEvent(e) for e in pending]

    # -- tick (ra_server.erl tick/1 + proc tick handling) ------------------

    def _tick(self) -> list:
        effects = list(self.machine.tick(time.time(), self.machine_state))
        effects.extend(self.log.tick(time.monotonic() * 1000.0))
        # lazily persist apply progress so recovery can dedup effects
        # (ra_log_meta last_applied; the reference batches through dets
        # with auto_save 5s, ra_log_meta.erl:32,53).  Throttled to the
        # same order — a full meta rewrite per 100ms tick was ~15% of
        # busy CPU under the classic bench; staleness only costs
        # effect-dedup precision on recovery.
        now = time.monotonic()
        if self.last_applied > self._persisted_last_applied and \
                now - self._last_meta_save >= 2.5:
            self._last_meta_save = now
            self.log.store_meta(sync=False, last_applied=self.last_applied)
            self._persisted_last_applied = self.last_applied
        return _filter_follower_effects(effects) \
            if self.raft_state != RaftState.LEADER else effects

    def flush_applied_watermark(self) -> None:
        """Persist the lazy last_applied watermark NOW — the clean-stop
        path (the reference's dets ra_log_meta flushes on close too).
        Recovery after a clean stop then suppresses every already-seen
        machine effect instead of replaying the up-to-2.5s-stale
        suffix; a crash still only costs effect-dedup precision."""
        if self.last_applied > self._persisted_last_applied:
            self.log.store_meta(sync=False, last_applied=self.last_applied)
            self._persisted_last_applied = self.last_applied

    def _next_snapshot_token(self) -> int:
        self._snapshot_token = getattr(self, "_snapshot_token", 0) + 1
        return self._snapshot_token

    #: give up on an unacknowledged snapshot transfer after this long —
    #: the functional stand-in for the reference's snapshot_sender DOWN
    #: (peer_snapshot_process_exited, ra_server.erl handle_down): resets
    #: the peer so the pipeline retries (possibly re-sending)
    SNAPSHOT_SEND_TIMEOUT_S = 5.0

    def _tick_leader(self) -> list:
        effects = self._tick()
        now = time.monotonic()
        for peer in self.cluster.values():
            if peer.status == PeerStatus.SENDING_SNAPSHOT and \
                    now - peer.snapshot_started > \
                    self.SNAPSHOT_SEND_TIMEOUT_S:
                peer.status = PeerStatus.NORMAL
                peer.snapshot_sender = None
        # refresh peers (periodic empty AERs stand in for ra's aten-driven
        # liveness; ra sends no idle heartbeats, INTERNALS.md:291-328)
        effects.extend(self._make_all_rpcs())
        return effects

    # -- aux state machinery (ra_machine handle_aux + ra_aux accessors) ----

    def handle_aux(self, kind: str, msg: Any, from_: Any = None) -> list:
        """Route an aux command/eval into the machine's handle_aux
        (ra_server.erl handle_aux dispatch; ra_aux gives the callback
        read access to server internals via ``internal=self``)."""
        result = self.effective_machine.handle_aux(
            self.raft_state.value, kind, msg, self.aux_state, self)
        effects: list = []
        reply = None
        if isinstance(result, tuple):
            if len(result) >= 1:
                self.aux_state = result[0]
            if len(result) >= 2 and result[1] is not None:
                effects = list(result[1]) if \
                    isinstance(result[1], (list, tuple)) else []
            if len(result) >= 3:
                reply = result[2]
        if from_ is not None and not any(isinstance(e, Reply)
                                         for e in effects):
            effects.append(Reply(from_, reply if reply is not None
                                 else "ok"))
        return effects

    # -- machine effects executed in the core (release_cursor etc.) --------

    def _cluster_spec_at(self, idx: int) -> tuple:
        """The configuration in force at log index ``idx``: the live
        view when the recorded change is at/below idx; else
        previous_cluster when ITS change index is at/below idx (one
        change in flight at a time makes it the config between the two
        newest changes); else the newest change found scanning the log
        down to the snapshot, whose meta cluster is the base case."""
        if self.cluster_index_term.index <= idx:
            return tuple((sid, p.membership)
                         for sid, p in self.cluster.items())
        if self.previous_cluster is not None and \
                self.previous_cluster[0].index <= idx:
            return self.previous_cluster[1]
        # fetch downward with an early break — the wanted change is
        # typically near idx; a forward read_range would materialize
        # the whole prefix first.  The memo bounds the scan: snapshot/
        # checkpoint effects arrive with monotonically growing indexes,
        # and everything at/below an earlier release cursor is committed
        # prefix, so on a change-free log the common case is O(new
        # entries since the last call), not O(log length).
        lo = self.log.first_index()
        cached = self._spec_cache
        # the memo only narrows the scan while the log still covers
        # (cached_idx, idx] in full — once the snapshot floor passes the
        # cached index a change may hide under the snapshot, and the
        # meta fallback below (newer information) must win instead
        use_cache = (cached is not None and cached[0] <= idx and
                     lo <= cached[0] + 1)
        if use_cache:
            lo = cached[0] + 1
        for i in range(idx, lo - 1, -1):
            e = self.log.fetch(i)
            if e is not None and isinstance(e.command,
                                            ClusterChangeCommand):
                spec = tuple(e.command.cluster)
                self._spec_cache = (idx, spec)
                return spec
        if use_cache:
            self._spec_cache = (idx, cached[1])
            return cached[1]
        meta = self.log.snapshot_meta()
        if meta is not None and meta.index <= idx:
            spec = tuple(meta.cluster)
            self._spec_cache = (idx, spec)
            return spec
        return tuple((sid, p.membership)
                     for sid, p in self.cluster.items())

    def _machine_version_at(self, idx: int) -> int:
        """The effective machine version at log index ``idx`` — the
        newest bump at or below it (machine_versions is newest-first).
        Stamping the LIVE version would mis-label a snapshot taken at
        an index below a just-applied bump (index_machine_version,
        ra_server.erl parity; the same stamp-at-index rule as
        _cluster_spec_at)."""
        for bump_idx, ver in self.machine_versions:
            if bump_idx <= idx:
                return ver
        return 0

    def handle_machine_effect(self, eff: Any) -> list:
        """Called by the shell for machine effects that mutate log state
        (ra_server.erl:2018-2046).

        The snapshot/checkpoint meta must record the configuration in
        force AT eff.index, not the live view: cluster changes take
        effect on append, so the view can contain an in-flight change
        NEWER than the snapshot point — if that change is later
        reverted, a snapshot stamped with it would immortalize a
        configuration that never existed, and installs would spread it
        (found by the combined fuzz; the reference stamps the live
        cluster, ra_server.erl:2018-2027, and shares the hazard)."""
        cluster_spec = self._cluster_spec_at(eff.index)
        mac_ver = self._machine_version_at(eff.index)
        if isinstance(eff, ReleaseCursor):
            return self.log.update_release_cursor(
                eff.index, cluster_spec, mac_ver, eff.machine_state)
        if isinstance(eff, Checkpoint):
            return self.log.checkpoint(
                eff.index, cluster_spec, mac_ver, eff.machine_state)
        if isinstance(eff, PromoteCheckpoint):
            self.log.promote_checkpoint(eff.index)
            return []
        return []

    # -- introspection -----------------------------------------------------

    def overview(self) -> dict:
        return {
            "id": self.id,
            "raft_state": self.raft_state.value,
            "current_term": self.current_term,
            "voted_for": self.voted_for,
            "leader_id": self.leader_id,
            "commit_index": self.commit_index,
            "last_applied": self.last_applied,
            "query_index": self.query_index,
            "membership": self.membership.value,
            "cluster_change_permitted": self.cluster_change_permitted,
            "machine_version": self.machine_version,
            "effective_machine_version": self.effective_machine_version,
            "cluster": {pid: {"match_index": p.match_index,
                              "next_index": p.next_index,
                              "status": p.status.value,
                              "membership": p.membership.value}
                        for pid, p in self.cluster.items()},
            "log": self.log.overview(),
        }


# ---------------------------------------------------------------------------
# module helpers
# ---------------------------------------------------------------------------

def _log_up_to_date(idx: int, term: int, last: IdxTerm) -> bool:
    """§5.4.1 up-to-date check (ra_server.erl:2486-2493)."""
    if term > last.term:
        return True
    if term == last.term and idx >= last.index:
        return True
    return False


def dict_from_cluster_spec(spec: tuple) -> dict:
    return {sid: Peer(membership=m) for sid, m in spec}


def _follower_catchup_predicate(event: Any, server: "RaServer") -> bool:
    """Condition is met when a message arrives that lets the follower make
    progress again: an AER whose prev point we can evaluate, or a snapshot
    (follower_catchup_cond_fun)."""
    if isinstance(event, AppendEntriesRpc):
        if event.term < server.current_term:
            return False
        if event.prev_log_index == 0:
            return True
        last_idx = server.last_idx_term().index
        return event.prev_log_index <= last_idx
    if isinstance(event, InstallSnapshotRpc):
        return event.term >= server.current_term
    return False


def _transfer_leadership_predicate(event: Any, server: "RaServer") -> bool:
    """Old leader waits in await_condition until it sees a message from the
    new leader (an AER/vote in a higher term)."""
    return isinstance(event, (AppendEntriesRpc, RequestVoteRpc, PreVoteRpc,
                              HeartbeatRpc))


def _wal_up_predicate(event: Any, server: "RaServer") -> bool:
    """wal_down condition exits when the restarted WAL announces itself
    (ra_server.erl:538-554 — the {error, wal_down} hold).  Liveness is
    re-checked: a stale WalUpEvent from an incarnation that already died
    again must not unpark the server — the replay would hit WalDown on
    the first append and drop the rest of the postponed backlog."""
    return isinstance(event, WalUpEvent) and \
        getattr(server.log, "wal_is_up", lambda: True)()


_FOLLOWER_SAFE_EFFECTS = (ReleaseCursor, Checkpoint, AuxEffect,
                          GarbageCollection, SendMsg, LogReadEffect, Monitor,
                          Reply, SendRpc, StartElectionTimeout,
                          NextEvent, Notify)
# NB: TimerEffect is NOT follower-safe — machine timers are armed by the
# leader only (they are absent from the keep-list of
# filter_follower_effects, ra_server.erl:1817-1860); the shell
# additionally drops an expiry that races a leadership loss.


def _filter_follower_effects(effects: list) -> list:
    """Followers suppress most machine effects — they are emitted by the
    leader only (filter_follower_effects, ra_server.erl:1815-1860).
    release_cursor/checkpoint/aux/gc and local sends are kept."""
    out = []
    for e in effects:
        if isinstance(e, Monitor) and e.component == "machine":
            continue
        if isinstance(e, Notify):
            continue
        if isinstance(e, SendMsg) and "local" not in e.options:
            continue
        if isinstance(e, Reply) and isinstance(e.msg, CommandResult) and \
                e.replier == "leader":
            # leader-replier consensus replies: follower copies dropped
            # ({reply,_,_,leader} filtering); member-replier replies
            # survive — the named member executes them at the shell
            continue
        if isinstance(e, _FOLLOWER_SAFE_EFFECTS):
            out.append(e)
    return out
