from .machine import ApplyMeta, JitMachine, Machine, SimpleMachine
from .server import Peer, RaServer
from .types import *  # noqa: F401,F403 — types is the vocabulary module
