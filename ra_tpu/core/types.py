"""Core wire/state types for the ra-tpu framework.

Message families mirror the reference protocol records
(/root/reference/src/ra.hrl:111-188): append_entries_rpc, append_entries_reply,
request_vote_rpc/result, pre_vote_rpc/result, install_snapshot_rpc/result,
heartbeat_rpc/reply.  Commands and reply modes mirror ra_server:command_type()
and ra_server:command_reply_mode() (/root/reference/src/ra_server.erl:100-140).

These are plain frozen dataclasses: the pure core consumes and produces them as
data.  The batched lane engine (ra_tpu.ops / ra_tpu.engine) re-encodes the hot
subset (AER replies, votes, heartbeats) into SoA integer arrays for the XLA
quorum kernels; these dataclasses remain the lingua franca of the host paths
(transport, log, tests).
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, NamedTuple, Optional, Union

# protocol version gate, exchanged in pre-vote only (ra.hrl:96-108)
RA_PROTO_VERSION = 1


class ServerId(NamedTuple):
    """{Name, Node} pair identifying one cluster member (ra:server_id())."""

    name: str
    node: str

    def __repr__(self) -> str:  # compact, log-friendly
        return f"{self.name}@{self.node}"


# ---------------------------------------------------------------------------
# Index/term bookkeeping
# ---------------------------------------------------------------------------

class IdxTerm(NamedTuple):
    index: int
    term: int


SNAPSHOT_NONE = IdxTerm(0, 0)  # "no entry"; log indexes are 1-based like ra


# ---------------------------------------------------------------------------
# Raft states (ra_server:ra_state(), ra_server.erl:142-150)
# ---------------------------------------------------------------------------

class RaftState(str, enum.Enum):
    LEADER = "leader"
    FOLLOWER = "follower"
    CANDIDATE = "candidate"
    PRE_VOTE = "pre_vote"
    AWAIT_CONDITION = "await_condition"
    RECEIVE_SNAPSHOT = "receive_snapshot"
    RECOVER = "recover"
    RECOVERED = "recovered"
    STOP = "stop"
    DELETE_AND_TERMINATE = "delete_and_terminate"


class Membership(str, enum.Enum):
    """Voting status of a member (ra:ra_membership())."""

    VOTER = "voter"
    NON_VOTER = "non_voter"
    PROMOTABLE = "promotable"  # non-voter that auto-promotes at target index
    UNKNOWN = "unknown"


class PeerStatus(str, enum.Enum):
    """Per-peer replication status (ra.hrl:51-54)."""

    NORMAL = "normal"
    SENDING_SNAPSHOT = "sending_snapshot"
    SUSPENDED = "suspended"
    DISCONNECTED = "disconnected"


# ---------------------------------------------------------------------------
# Commands
# ---------------------------------------------------------------------------

class ReplyMode(str, enum.Enum):
    """When/how the caller learns about its command (ra_server.erl:117-131)."""

    AFTER_LOG_APPEND = "after_log_append"
    AWAIT_CONSENSUS = "await_consensus"
    NOTIFY = "notify"  # carries (correlation, pid) in the command
    NOREPLY = "noreply"


class Priority(str, enum.Enum):
    NORMAL = "normal"
    LOW = "low"


@dataclass(frozen=True, slots=True)
class UserCommand:
    """'$usr' — a command for the user state machine.

    ``slots=True`` because this is the highest-volume object on the
    classic plane: one instance per client command, created on the
    ingress path at up-to-100k/s rates (ISSUE 13) — the slotted form
    drops per-instance dict allocation from the hot path."""

    data: Any
    reply_mode: ReplyMode = ReplyMode.AWAIT_CONSENSUS
    correlation: Any = None  # used with ReplyMode.NOTIFY
    notify_to: Any = None    # destination for applied-notifications
    from_: Any = None        # reply destination, attached at append time
    #: which member answers an await_consensus call: None/"leader", or
    #: ("member", ServerId) — the reply_from command option
    #: (ra.erl:786-823); useful when the caller sits nearer a follower
    reply_from: Any = None
    #: causal trace context minted at ingress (ISSUE 7): a short string
    #: id that rides the command through append/replication/WAL/apply
    #: so the flight recorder's hop events join into one timeline.
    #: None = untraced (the cost of the disabled path is one
    #: ``is not None`` test per hop).
    trace: Any = None

    kind = "usr"


@dataclass(frozen=True)
class NoopCommand:
    """'$noop' appended by a new leader; carries effective machine version
    (ra_server.erl:839-859, applied at :2671-2731)."""

    machine_version: int

    kind = "noop"


@dataclass(frozen=True)
class JoinCommand:
    """'$ra_join' — add a member (ra.erl:593-602)."""

    server_id: ServerId
    membership: Membership = Membership.VOTER
    reply_mode: ReplyMode = ReplyMode.AWAIT_CONSENSUS
    from_: Any = None

    kind = "ra_join"


@dataclass(frozen=True)
class LeaveCommand:
    """'$ra_leave' — remove a member (ra.erl:628)."""

    server_id: ServerId
    reply_mode: ReplyMode = ReplyMode.AWAIT_CONSENSUS
    from_: Any = None

    kind = "ra_leave"


@dataclass(frozen=True)
class ClusterDeleteCommand:
    """'$ra_cluster' delete — orderly cluster teardown (ra.erl:556)."""

    reply_mode: ReplyMode = ReplyMode.AWAIT_CONSENSUS
    from_: Any = None

    kind = "ra_cluster_delete"


@dataclass(frozen=True)
class ClusterChangeCommand:
    """'$ra_cluster_change' — the full new cluster, appended by the leader
    when it processes a join/leave (ra_server.erl:2798-2915).  ``cluster`` is
    a tuple of (ServerId, Membership) pairs — the complete new membership."""

    cluster: tuple
    reply_mode: ReplyMode = ReplyMode.AWAIT_CONSENSUS
    correlation: Any = None
    notify_to: Any = None
    from_: Any = None

    kind = "ra_cluster_change"


Command = Union[UserCommand, NoopCommand, JoinCommand, LeaveCommand,
                ClusterChangeCommand, ClusterDeleteCommand]


class Entry(NamedTuple):
    """One log entry: {Index, Term, Command} (ra:log_entry())."""

    index: int
    term: int
    command: Command


# ---------------------------------------------------------------------------
# RPC message families (ra.hrl:111-188)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class AppendEntriesRpc:
    term: int
    leader_id: ServerId
    prev_log_index: int
    prev_log_term: int
    leader_commit: int
    entries: tuple = ()  # tuple[Entry, ...]
    #: OPTIONAL encoded durable images parallel to ``entries`` (ISSUE
    #: 13): the leader already holds each entry's WAL payload bytes in
    #: its memtable, and shipping them lets followers feed their WAL
    #: without re-encoding (the batch-append path skips one pickle per
    #: entry per follower).  None when the leader's bytes are gone
    #: (segment-flushed catch-up) — followers then encode themselves.
    payloads: Optional[tuple] = None


@dataclass(frozen=True)
class AppendEntriesReply:
    term: int
    success: bool
    # ra's reply carries next_index + last matched idx/term rather than a
    # simple conflict index (ra.hrl:127-137)
    next_index: int
    last_index: int
    last_term: int
    from_: ServerId = None  # filled by transport/shell when routing


@dataclass(frozen=True)
class RequestVoteRpc:
    term: int
    candidate_id: ServerId
    last_log_index: int
    last_log_term: int


@dataclass(frozen=True)
class RequestVoteResult:
    term: int
    vote_granted: bool
    from_: ServerId = None


@dataclass(frozen=True)
class PreVoteRpc:
    term: int
    token: Any
    candidate_id: ServerId
    version: int  # protocol version, gated here only (ra.hrl:96-108)
    machine_version: int
    last_log_index: int
    last_log_term: int


@dataclass(frozen=True)
class PreVoteResult:
    term: int
    token: Any
    vote_granted: bool
    from_: ServerId = None


@dataclass(frozen=True)
class SnapshotMeta:
    """Snapshot metadata (ra_snapshot:meta())."""

    index: int
    term: int
    cluster: tuple  # tuple[(ServerId, Membership), ...]
    machine_version: int


@dataclass(frozen=True)
class InstallSnapshotRpc:
    term: int
    leader_id: ServerId
    meta: SnapshotMeta
    chunk_number: int
    chunk_flag: str  # "next" | "last"
    data: bytes
    #: crc32 of ``data`` — validated per chunk on accept so a corrupt
    #: transfer aborts early instead of poisoning the assembled snapshot
    #: (ra_log_snapshot.erl:73-111); -1 = absent (old peers)
    chunk_crc: int = -1
    #: transfer identity, echoed in the result so the leader can reject
    #: stragglers from an abandoned (timed-out) transfer
    token: Any = None


@dataclass(frozen=True)
class InstallSnapshotResult:
    term: int
    last_index: int
    last_term: int
    from_: ServerId = None
    token: Any = None  # echoes InstallSnapshotRpc.token


@dataclass(frozen=True)
class HeartbeatRpc:
    """Consistent-query heartbeat (ra.hrl:176-188)."""

    query_index: int
    term: int
    leader_id: ServerId


@dataclass(frozen=True)
class HeartbeatReply:
    query_index: int
    term: int
    from_: ServerId = None


RaMsg = Union[AppendEntriesRpc, AppendEntriesReply, RequestVoteRpc,
              RequestVoteResult, PreVoteRpc, PreVoteResult,
              InstallSnapshotRpc, InstallSnapshotResult,
              HeartbeatRpc, HeartbeatReply]


# ---------------------------------------------------------------------------
# Non-RPC events fed to the core by the shell
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ElectionTimeout:
    pass


@dataclass(frozen=True)
class CommandEvent:
    """A client command arriving at this server ({command, Priority, Cmd})."""

    command: Command
    priority: Priority = Priority.NORMAL
    from_: Any = None  # reply destination for call-style commands


@dataclass(frozen=True)
class CommandsEvent:
    """Flushed batch of low-priority commands ({commands, Cmds}).

    ``images`` (ISSUE 18) optionally carries the commands' codec payload
    images, aligned 1:1 with ``commands``: a batch that arrived over the
    wire was already encoded ONCE at the client, so the leader appends
    the shipped bytes (and a follower relays them) instead of
    re-encoding — the encode-once contract of ra_tpu.codec."""

    commands: tuple
    images: Optional[tuple] = None


@dataclass(frozen=True)
class WrittenEvent:
    """{ra_log_event, {written, Term, {From, To}}} from the WAL."""

    from_index: int
    to_index: int
    term: int


@dataclass(frozen=True)
class LogEvent:
    """Other ra_log_event payloads routed into the log facade."""

    payload: Any


@dataclass(frozen=True)
class WalUpEvent:
    """The WAL was restarted after a crash: cores parked in
    await_condition(wal_down) may resume (the new-wal-pid signal a
    reference server observes via ra_log, ra_log.erl:778-793)."""

    generation: int = 0


@dataclass(frozen=True)
class DownEvent:
    """Process-down notification (monitor fired)."""

    target: Any
    reason: Any = None


@dataclass(frozen=True)
class UpEvent:
    """Process-up notification: a co-hosted member (re)started.  The
    DownEvent twin for the in-process deployment (ISSUE 13): a kill
    broadcast DownEvent to co-hosted siblings — the leader marked the
    peer DISCONNECTED and stopped replicating to it — but a restart
    had no up edge, so a restarted follower with a shorter log wedged
    forever (it cannot win pre-votes, and the leader never resumes its
    catch-up).  Cross-node deployments heal through the transport
    failure detector's NodeEvent("up"); this is the same verdict at
    member granularity for siblings that share a node."""

    target: Any


@dataclass(frozen=True)
class NodeEvent:
    """Failure-detector verdict for a node: up | down."""

    node: str
    status: str


@dataclass(frozen=True)
class TickEvent:
    """Periodic maintenance tick (ra_server:tick/1)."""

    pass


@dataclass(frozen=True)
class ConsistentQueryEvent:
    query_fn: Any
    from_: Any = None


@dataclass(frozen=True)
class TransferLeadershipEvent:
    target: ServerId
    from_: Any = None


@dataclass(frozen=True)
class ForceMemberChangeEvent:
    """Disaster-recovery escape hatch: shrink the cluster to THIS member
    only, then self-elect — used when a permanent majority outage makes
    normal membership changes impossible
    (force_shrink_members_to_current_member,
    ra_server_proc.erl:234-236, ra_server.erl:1320-1328)."""

    from_: Any = None


@dataclass(frozen=True)
class ForceElectionEvent:
    """trigger_election — skip pre-vote, go straight to candidate."""

    pass


@dataclass(frozen=True)
class AuxCommandEvent:
    """{aux_command, Type, Cmd} — routed to the machine's handle_aux
    (ra.erl aux_command/cast_aux_command)."""

    cmd: Any
    from_: Any = None


#: reserved server name addressing a NODE's control plane rather than a
#: member (the rpc:call target role of ra_server_sup_sup.erl:42-130)
NODE_SCOPE = "$node"


@dataclass(frozen=True)
class NodeControlEvent:
    """Node-lifecycle RPC: start/restart/stop/force-delete a member on
    the receiving node (ra_server_sup_sup's start_server_rpc /
    restart_server_rpc / prepare_server_stop_rpc).  Picklable — args
    carry config snapshots and machine SPECS, never live objects."""

    op: str
    args: dict
    from_: Any = None


# ---------------------------------------------------------------------------
# Effects — returned by the pure core / machine, executed by the shell
# (ra_machine.erl:121-142 + ra_server internal effects)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class SendRpc:
    """Async cast to a peer; must never block (ra_server_proc.erl:1317-1341)."""

    to: ServerId
    msg: RaMsg


@dataclass(frozen=True)
class SendVoteRequests:
    """Fan-out vote/pre-vote requests in parallel (ra_server_proc.erl:1495)."""

    requests: tuple  # tuple[(ServerId, RaMsg), ...]


@dataclass(frozen=True)
class Reply:
    """Reply to a synchronous caller.

    ``replier`` picks WHICH member sends it ({reply, From, Reply,
    Replier}, ra_server.erl:2771-2781): "leader" (default — follower
    copies are filtered) or ("member", ServerId) — every member emits
    the effect, the shell executes it only on the named member.  The
    reply value is deterministic across replicas, so any member's copy
    is THE reply."""

    to: Any
    msg: Any
    replier: Any = "leader"


@dataclass(frozen=True)
class NextEvent:
    """Re-inject an event into the core immediately."""

    event: Any


@dataclass(frozen=True)
class SendMsg:
    """Machine effect: send an arbitrary message (ra_machine.erl:121-127).
    options: as_ra_event / cast / local."""

    to: Any
    msg: Any
    options: tuple = ()


@dataclass(frozen=True)
class AppendEffect:
    """{append, Cmd} / {append, Cmd, ReplyMode} machine effect
    (ra_machine.erl:128-130): the machine asks the LEADER to append a
    follow-up user command from apply/3.  Executed by re-entering the
    command path (ra_server_proc.erl:1377-1382); followers drop it
    (filter_follower_effects — only the leader originates the append,
    every member then applies it through normal replication)."""

    data: Any
    reply_mode: "ReplyMode" = None  # None -> noreply
    correlation: Any = None         # for ReplyMode.NOTIFY
    notify_to: Any = None


@dataclass(frozen=True)
class ModCall:
    fn: Any
    args: tuple = ()


@dataclass(frozen=True)
class Notify:
    """Applied-notification batch: {applied, [{Correlation, Reply}]}."""

    to: Any
    correlations: tuple  # tuple[(correlation, reply), ...]


@dataclass(frozen=True)
class Monitor:
    kind: str  # "process" | "node"
    target: Any
    component: str = "machine"  # machine|aux|snapshot_sender|snapshot_writer|log


@dataclass(frozen=True)
class Demonitor:
    kind: str
    target: Any
    component: str = "machine"


@dataclass(frozen=True)
class TimerEffect:
    name: Any
    ms: Optional[int]  # None cancels
    msg: Any = None


@dataclass(frozen=True)
class LogReadEffect:
    """Machine effect {log, Indexes, Fun[, {local, Node}]}: read back
    committed entries (ra_machine.erl:136-137).

    Reference parity: the BARE effect executes on EVERY member that
    applies the command (filter_follower_effects keeps it,
    ra_server.erl:1837-1838; executed in any raft state,
    ra_server_proc.erl:1383-1397) — the fn must be idempotent or
    deduplicate via its closure.  ``local`` restricts execution to the
    named node (the {local, Node} option, :1369-1376).  Effects
    returned by fn are executed in place (the reference's recursion)."""

    indexes: tuple
    fn: Any
    local: Any = None  # node name, or None = every member


@dataclass(frozen=True)
class ReleaseCursor:
    """Machine effect: log can be truncated up to index; snapshot state."""

    index: int
    machine_state: Any


@dataclass(frozen=True)
class Checkpoint:
    """Machine effect: cheap state dump that does NOT truncate the log."""

    index: int
    machine_state: Any


@dataclass(frozen=True)
class PromoteCheckpoint:
    index: int


@dataclass(frozen=True)
class AuxEffect:
    msg: Any


@dataclass(frozen=True)
class GarbageCollection:
    pass


@dataclass(frozen=True)
class StartElectionTimeout:
    """Shell should (re)arm the election timer (ra_server_proc.erl:1638-1657)."""

    kind: str = "medium"  # really_short | short | medium | long


@dataclass(frozen=True)
class CancelElectionTimeout:
    pass


@dataclass(frozen=True)
class SendSnapshot:
    """Leader side: spawn a chunked snapshot send to peer
    (ra_server_proc.erl:1446-1488)."""

    to: ServerId
    id_term: tuple  # (leader_id, term)
    token: Any = None  # transfer identity (stamped on the peer)


@dataclass(frozen=True)
class RecordLeader:
    """Leaderboard update: cluster name -> (leader, members)."""

    cluster_name: str
    leader: Optional[ServerId]
    members: tuple


@dataclass(frozen=True)
class IncrementMetric:
    name: str
    amount: int = 1


Effect = Any  # union of the above; kept open for machine-defined effects

Effects = list  # list[Effect]


# ---------------------------------------------------------------------------
# Replies sent back to clients by the shell
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class CommandResult:
    """Successful command outcome: {ok, Reply, Leader}."""

    index: int
    term: int
    reply: Any = None  # None for after_log_append acks
    leader: Optional[ServerId] = None


@dataclass(frozen=True)
class ErrorResult:
    reason: Any
    leader: Optional[ServerId] = None


def strip_local_handles(cmd: Any) -> Any:
    """Drop process-local reply handles (futures/callables) from a command
    before it leaves the process (wire or disk).  Replies are only ever
    owed by the member that accepted the call; remote/recovered copies
    never fire them (recovery replays with effects suppressed,
    ra_server.erl:376-414)."""
    from dataclasses import replace as _replace
    out = cmd
    for field_ in ("from_", "notify_to"):
        v = getattr(out, field_, None)
        if v is not None and not isinstance(v, (str, int, tuple)):
            out = _replace(out, **{field_: None})
    return out


def strip_msg_handles(msg: Any) -> Any:
    """Sanitize an outbound RPC: AER entries may embed commands carrying
    local reply handles."""
    if isinstance(msg, AppendEntriesRpc) and msg.entries:
        from dataclasses import replace as _replace
        entries = tuple(
            Entry(e.index, e.term, strip_local_handles(e.command))
            for e in msg.entries)
        return _replace(msg, entries=entries)
    return msg


# ---------------------------------------------------------------------------
# Server configuration (ra_server:ra_server_config(), ra_server.erl:188-213)
# ---------------------------------------------------------------------------

#: tunables persisted in (and restored from) the directory's config
#: snapshot beyond the always-present identity/timing fields — ONE list
#: shared by the snapshot writer and both restore sites, so adding a
#: tunable cannot silently stop round-tripping through recovery
SNAPSHOT_TUNABLE_KEYS = (
    "await_condition_timeout_ms", "max_pipeline_count",
    "max_append_entries_batch", "max_append_entries_bytes",
    "command_flush_size", "snapshot_chunk_size",
    "install_snap_rpc_timeout_ms", "friendly_name",
)


@dataclass
class ServerConfig:
    server_id: ServerId
    uid: str
    cluster_name: str
    initial_members: tuple  # tuple[ServerId, ...]
    machine: Any  # Machine instance (ra_tpu.core.machine.Machine)
    log_init_args: dict = field(default_factory=dict)
    # election tuning (ms); shell maps StartElectionTimeout kinds onto these
    broadcast_time_ms: int = 100
    election_timeout_ms: int = 1000
    tick_interval_ms: int = 1000
    await_condition_timeout_ms: int = 3000
    max_pipeline_count: int = 4096   # ra_server.hrl:7
    #: entries per AppendEntries frame.  The reference ships 128
    #: (ra_server.hrl:8); with the batch-native follower path (ONE
    #: append + ONE WAL fan-in submit + ONE cumulative reply per
    #: frame, ISSUE 13) deeper frames amortize strictly further, so
    #: the default rides the byte bound below instead
    max_append_entries_batch: int = 1024
    #: byte bound on one AppendEntries frame (ISSUE 13): a batch closes
    #: when EITHER the entry cap or this payload-byte budget is reached
    #: (evaluated against the encoded durable images when the leader
    #: holds them), so a burst of large commands cannot build a frame
    #: that stalls the socket behind one send
    max_append_entries_bytes: int = 1 << 20
    #: how many buffered low-priority commands flush into one
    #: {commands, Batch} event (ISSUE 13).  The reference's
    #: ?FLUSH_COMMANDS_SIZE is 16 (ra_server.hrl:11); the batch-native
    #: append path amortizes its one-lock/one-WAL-submit cost over the
    #: whole event, so a deeper default flush is strictly cheaper until
    #: frames hit max_append_entries_* bounds (512 measured best on the
    #: classic bench; 1024 starts trading latency for nothing)
    command_flush_size: int = 512
    snapshot_chunk_size: int = 1024 * 1024  # ra_server.hrl:9
    install_snap_rpc_timeout_ms: int = 30_000
    membership: Membership = Membership.VOTER
    friendly_name: Optional[str] = None
    counters: Any = None
    system_name: str = "default"
