"""Read-only accessors into server internals for aux handlers — ra_aux.

The reference lets ``handle_aux`` callbacks inspect the server through an
opaque internal state handle (ra_aux.erl:25-67: machine_state/1,
leader_id/1, members/1, overview/1, log_fetch/2, log_stats/1, ...).
Here the handle is the RaServer itself, passed as the last argument of
``Machine.handle_aux``; these functions are the sanctioned read surface
over it — aux handlers must not mutate the server.
"""
from __future__ import annotations

from typing import Any, Optional


def machine_state(internal) -> Any:
    """ra_aux:machine_state/1."""
    return internal.machine_state


def leader_id(internal):
    """ra_aux:leader_id/1."""
    return internal.leader_id


def current_term(internal) -> int:
    """ra_aux:current_term/1."""
    return internal.current_term


def members(internal) -> list:
    """ra_aux:members/1 — cluster member ids."""
    return list(internal.cluster)


def effective_machine_version(internal) -> int:
    """ra_aux:effective_machine_version/1."""
    return internal.effective_machine_version


def overview(internal) -> dict:
    """ra_aux:overview/1."""
    return internal.overview()


def log_last_index_term(internal):
    """ra_aux:log_last_index_term/1."""
    return internal.log.last_index_term()


def log_fetch(idx: int, internal) -> Optional[Any]:
    """ra_aux:log_fetch/2 — a committed entry by index (None when
    truncated or out of range)."""
    return internal.log.fetch(idx)


def log_stats(internal) -> dict:
    """ra_aux:log_stats/1."""
    return internal.log.overview()
