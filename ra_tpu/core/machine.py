"""User state-machine behaviour — the ra_machine equivalent.

Mirrors the callback contract of /root/reference/src/ra_machine.erl:233-287:
mandatory ``init/1`` + ``apply/3``; optional ``state_enter/2``, ``tick/2``,
``snapshot_installed/4``, aux handlers, ``overview/1`` and versioning
(``version/0`` + ``which_module/1``).

Two flavours exist:

* :class:`Machine` — the classic host-side behaviour.  ``apply`` runs in
  Python on the host, may return arbitrary effects, and state may be any
  Python object.  This is always available and is the default.
* :class:`JitMachine` — the TPU-native variant (the ``ra_machine_xla`` of the
  north star).  Its ``apply`` must be a pure, shape-stable JAX function
  ``(meta_array, cmd_array, state_pytree) -> (state_pytree, reply_array)``
  so committed batches can be folded on-device by the lane engine — via
  ``lax.scan`` or, for commutative machines, the one-shot
  ``jit_apply_batch`` window fold (see ra_tpu/engine/lockstep.py, step 5).
  A JitMachine also provides the host-side protocol so the same machine
  works on both paths.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

from .types import Effects


@dataclass(frozen=True)
class ApplyMeta:
    """Metadata passed to apply/3 (ra_machine:command_meta_data())."""

    index: int
    term: int
    system_time: float = 0.0
    machine_version: int = 0
    from_: Any = None
    reply_mode: Any = None


class Machine:
    """Base class for host-side state machines.

    Subclasses must override :meth:`init` and :meth:`apply`.  All other
    callbacks have no-op defaults matching the optional-callback semantics of
    the reference (ra_machine.erl:211-221).
    """

    #: bump when apply semantics change; see version gating in the core
    #: (ra_server.erl:2671-2732)
    version: int = 0

    def init(self, config: dict) -> Any:
        raise NotImplementedError

    def apply(self, meta: ApplyMeta, command: Any, state: Any):
        """Apply a committed user command.

        Returns ``(new_state, reply)`` or ``(new_state, reply, effects)``.
        """
        raise NotImplementedError

    # -- optional callbacks -------------------------------------------------

    def state_enter(self, raft_state: str, state: Any) -> Effects:
        return []

    def tick(self, time_ms: float, state: Any) -> Effects:
        return []

    def snapshot_installed(self, meta, state, old_meta, old_state) -> Effects:
        return []

    def init_aux(self, name: str) -> Any:
        return None

    def handle_aux(self, raft_state: str, msg_type: str, msg: Any,
                   aux_state: Any, internal) -> tuple:
        """Returns (aux_state, effects)."""
        return aux_state, []

    def overview(self, state: Any) -> Any:
        return state

    def which_module(self, version: int) -> "Machine":
        """Machine-version dispatch (ra_machine.erl:346-362).  Return the
        machine implementing ``version``; default: self for all versions."""
        return self

    def snapshot_module(self):
        """Override to customise the snapshot format (ra_machine.erl:435)."""
        return None

    def live_indexes(self, state: Any) -> list:
        return []


class SimpleMachine(Machine):
    """Wraps a plain ``fun(command, state) -> state`` as a machine — the
    ``{simple, Fun, Init}`` config variant (ra_machine_simple.erl, selected in
    ra_server.erl:277-283).  Replies are the new state."""

    def __init__(self, fn: Callable[[Any, Any], Any], initial_state: Any):
        self._fn = fn
        self._initial = initial_state

    def init(self, config: dict) -> Any:
        return self._initial

    def apply(self, meta: ApplyMeta, command: Any, state: Any):
        new_state = self._fn(command, state)
        return new_state, new_state


#: compiled host-path apply fns shared across same-config instances
_HOST_APPLY_JIT_CACHE: dict = {}


class JitMachine(Machine):
    """TPU-native machine: committed commands are dense arrays folded
    on-device.

    Contract (enforced by the lane engine, not here):

    * ``state`` is a JAX pytree of fixed-shape arrays (one leading lane axis
      when used under the batched engine).
    * :meth:`jit_apply` is pure and traceable: it is called under ``jit`` /
      ``vmap`` / ``lax.scan`` and must not use data-dependent Python control
      flow.
    * :meth:`encode_command` / :meth:`decode_reply` convert between host
      commands and the dense on-device representation.
    """

    #: shape/dtype spec of one encoded command, e.g. ("int32", (2,))
    command_spec: tuple = ("int32", ())
    #: shape/dtype spec of one reply
    reply_spec: tuple = ("int32", ())

    #: set True and override jit_apply_batch when the machine can fold a
    #: whole committed window in one shot (commutative/associative applies);
    #: the engine then skips the sequential lax.scan — O(1) depth instead
    #: of O(window)
    supports_batch_apply: bool = False

    def jit_init(self, n_lanes: int) -> Any:
        """Return the initial state pytree with a leading lane axis."""
        raise NotImplementedError

    def jit_apply(self, meta, command, state):
        """Pure JAX apply: (meta arrays, encoded cmd, state) -> (state, reply)."""
        raise NotImplementedError

    def jit_apply_batch(self, meta, commands, mask, state):
        """Fold a window of commands at once.  commands: [..., A, C];
        mask: bool[..., A] (True = apply); state leading dims match the
        ... prefix.  Returns the new state.  Only called when
        supports_batch_apply is True."""
        raise NotImplementedError

    def encode_command(self, command: Any):
        raise NotImplementedError

    def decode_reply(self, reply_array) -> Any:
        return reply_array

    # -- host-side protocol so JitMachines also run on the classic path ----

    def init(self, config: dict) -> Any:
        import numpy as np  # local import: host path only
        import jax
        state = self.jit_init(1)
        return jax.tree.map(lambda x: np.asarray(x)[0], state)

    def apply(self, meta: ApplyMeta, command: Any, state: Any):
        import jax.numpy as jnp
        import jax
        # jit once per (class, scalar config): an eager jit_apply
        # re-traces control-flow primitives (lax.fori_loop bodies) on
        # every call, turning each classic-path apply into a fresh
        # compile — and caching per-instance would still compile once
        # per cluster member.  Sound because jit_apply is pure in
        # (meta, command, state) given the config (the class contract
        # above) — but only when the whole config is scalar: a machine
        # holding non-scalar config (arrays, tuples) falls back to a
        # per-instance compile, since two such instances could share
        # every scalar attr yet differ in behavior.
        attrs = [(k, v) for k, v in sorted(self.__dict__.items())
                 if not k.startswith("_")]
        if all(isinstance(v, (int, float, str, bool)) for _k, v in attrs):
            key = (type(self), tuple(attrs))
            fn = _HOST_APPLY_JIT_CACHE.get(key)
        else:
            # non-scalar config: keep the compile on the instance itself
            # (an id()-keyed shared cache could alias a GC'd instance)
            key = None
            fn = self.__dict__.get("_host_apply_jit")
        if fn is None:
            bound = type(self).jit_apply
            inst = self
            fn = jax.jit(lambda m, c, s: bound(inst, m, c, s))
            if key is not None:
                _HOST_APPLY_JIT_CACHE[key] = fn
            else:
                self.__dict__["_host_apply_jit"] = fn
        meta_arr = {"index": jnp.int32(meta.index), "term": jnp.int32(meta.term)}
        enc = self.encode_command(command)
        new_state, reply = fn(meta_arr, enc, state)
        return new_state, self.decode_reply(reply)
