"""User state-machine behaviour — the ra_machine equivalent.

Mirrors the callback contract of /root/reference/src/ra_machine.erl:233-287:
mandatory ``init/1`` + ``apply/3``; optional ``state_enter/2``, ``tick/2``,
``snapshot_installed/4``, aux handlers, ``overview/1`` and versioning
(``version/0`` + ``which_module/1``).

Two flavours exist:

* :class:`Machine` — the classic host-side behaviour.  ``apply`` runs in
  Python on the host, may return arbitrary effects, and state may be any
  Python object.  This is always available and is the default.
* :class:`JitMachine` — the TPU-native variant (the ``ra_machine_xla`` of the
  north star).  Its ``apply`` must be a pure, shape-stable JAX function
  ``(meta_array, cmd_array, state_pytree) -> (state_pytree, reply_array)``
  so committed batches can be folded on-device by the lane engine — via
  ``lax.scan`` or the one-shot ``jit_apply_batch`` window fold (see
  ra_tpu/engine/lockstep.py, step 5).  The window fold must preserve
  command ORDER; commutative machines fold trivially, and order-dependent
  ones may fold vectorized when the algebra allows (see jit_fifo/jit_kv).
  A JitMachine also provides the host-side protocol so the same machine
  works on both paths.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional

from .types import Effects


def cond_concrete(pred, true_fn, false_fn, operands):
    """``lax.cond`` that short-circuits in Python when ``pred`` is
    concrete (host/eager calls): picks the branch without tracing the
    other, avoiding lax.cond's per-call branch retrace outside jit.
    Under tracing it is exactly ``lax.cond``.

    Concreteness is probed by ``bool(pred)`` rather than an
    ``isinstance(pred, jax.core.Tracer)`` check: ``jax.core.Tracer`` is
    a deprecated public alias slated for removal, while a tracer
    refusing bool() (TracerBoolConversionError) is the stable,
    documented contract."""
    import jax
    from jax import lax

    try:
        concrete = bool(pred)  # ra13-ok: the sanctioned concreteness probe — TracerBoolConversionError is caught and routes traced preds to lax.cond
    except jax.errors.TracerBoolConversionError:
        return lax.cond(pred, true_fn, false_fn, operands)
    return true_fn(operands) if concrete else false_fn(operands)


@dataclass(frozen=True, slots=True)
class ApplyMeta:
    """Metadata passed to apply/3 (ra_machine:command_meta_data()).
    Slotted: one instance per applied command on every member — the
    apply fold is the classic plane's hottest loop (ISSUE 13)."""

    index: int
    term: int
    system_time: float = 0.0
    machine_version: int = 0
    from_: Any = None
    reply_mode: Any = None


class Machine:
    """Base class for host-side state machines.

    Subclasses must override :meth:`init` and :meth:`apply`.  All other
    callbacks have no-op defaults matching the optional-callback semantics of
    the reference (ra_machine.erl:211-221).
    """

    #: bump when apply semantics change; see version gating in the core
    #: (ra_server.erl:2671-2732)
    version: int = 0

    def init(self, config: dict) -> Any:
        raise NotImplementedError

    def apply(self, meta: ApplyMeta, command: Any, state: Any):
        """Apply a committed user command.

        Returns ``(new_state, reply)`` or ``(new_state, reply, effects)``.
        """
        raise NotImplementedError

    #: OPTIONAL batched apply (ISSUE 13): when a machine sets this to a
    #: callable ``apply_batch(meta, commands, state) -> (state, replies)``
    #: (or ``(state, replies, effects)``), the core's apply fold hands it
    #: RUNS of contiguous same-term plain user commands in one call
    #: instead of one :meth:`apply` per entry.  ``meta`` describes the
    #: FIRST entry of the run; command ``i`` applied at ``meta.index + i``
    #: (machines that key on the index compute it that way).  ``replies``
    #: must be one reply per command, in order — they feed the same
    #: notify/await-consensus plumbing the per-entry path feeds.  The
    #: contract is exact order equivalence with folding :meth:`apply`
    #: over the run; machines whose apply has per-command effects should
    #: leave this None (the default) and take the per-entry path.
    apply_batch = None

    # -- optional callbacks -------------------------------------------------

    def state_enter(self, raft_state: str, state: Any) -> Effects:
        return []

    def tick(self, time_ms: float, state: Any) -> Effects:
        return []

    def snapshot_installed(self, meta, state, old_meta, old_state) -> Effects:
        return []

    def init_aux(self, name: str) -> Any:
        return None

    def handle_aux(self, raft_state: str, msg_type: str, msg: Any,
                   aux_state: Any, internal) -> tuple:
        """Returns (aux_state, effects)."""
        return aux_state, []

    def overview(self, state: Any) -> Any:
        return state

    def which_module(self, version: int) -> "Machine":
        """Machine-version dispatch (ra_machine.erl:346-362).  Return the
        machine implementing ``version``; default: self for all versions."""
        return self

    def snapshot_module(self):
        """Override to customise the snapshot format (ra_machine.erl:435)."""
        return None

    def live_indexes(self, state: Any) -> list:
        return []


class SimpleMachine(Machine):
    """Wraps a plain ``fun(command, state) -> state`` as a machine — the
    ``{simple, Fun, Init}`` config variant (ra_machine_simple.erl, selected in
    ra_server.erl:277-283).  Replies are the new state."""

    def __init__(self, fn: Callable[[Any, Any], Any], initial_state: Any):
        self._fn = fn
        self._initial = initial_state

    def init(self, config: dict) -> Any:
        return self._initial

    def apply(self, meta: ApplyMeta, command: Any, state: Any):
        new_state = self._fn(command, state)
        return new_state, new_state


#: compiled host-path apply fns shared across same-config instances
_HOST_APPLY_JIT_CACHE: dict = {}


class JitMachine(Machine):
    """TPU-native machine: committed commands are dense arrays folded
    on-device.

    Contract (enforced by the lane engine, not here):

    * ``state`` is a JAX pytree of fixed-shape arrays (one leading lane axis
      when used under the batched engine).
    * :meth:`jit_apply` is pure and traceable: it is called under ``jit`` /
      ``vmap`` / ``lax.scan`` and must not use data-dependent Python control
      flow.
    * :meth:`encode_command` / :meth:`decode_reply` convert between host
      commands and the dense on-device representation.
    """

    #: shape/dtype spec of one encoded command, e.g. ("int32", (2,))
    command_spec: tuple = ("int32", ())
    #: shape/dtype spec of one reply
    reply_spec: tuple = ("int32", ())

    #: OPTIONAL vectorized read path (ISSUE 20): shape/dtype spec of one
    #: encoded query, or None when the machine has no jittable query
    #: kernel (the engine's lease/read-index plane then refuses reads
    #: for it).  Unlike commands, queries NEVER mutate state and never
    #: enter the log — the lane engine evaluates them against the
    #: leader replica once lease/read-index authority certifies the
    #: watermark (the consistent_query contract, ra_server.erl:3032+,
    #: with zero log appends).
    query_spec: Optional[tuple] = None
    #: shape/dtype spec of one query reply
    query_reply_spec: tuple = ("int32", ())

    #: set True when jit_apply_batch folds a whole committed window in
    #: one shot FASTER than the engine's representative lax.scan.  The
    #: fold must be IN ORDER-equivalent to applying the masked commands
    #: sequentially — commutativity is sufficient but not necessary
    #: (jit_fifo/jit_kv fold order-dependent vocabularies vectorized,
    #: falling back to sequential_window_fold for the hard windows)
    supports_batch_apply: bool = False

    def jit_init(self, n_lanes: int) -> Any:
        """Return the initial state pytree with a leading lane axis."""
        raise NotImplementedError

    def jit_apply(self, meta, command, state):
        """Pure JAX apply: (meta arrays, encoded cmd, state) -> (state, reply)."""
        raise NotImplementedError

    def jit_query(self, queries, state):
        """Pure vectorized read kernel (ISSUE 20): evaluate a window of
        encoded queries against ONE replica's machine state.

        ``queries``: [..., Kr, Cq] with Cq from :attr:`query_spec` and
        arbitrary leading (lane) dims; ``state``: the machine pytree
        with the SAME leading dims (the engine hands it the leader
        replica, member axis already gathered away).  Returns replies
        [..., Kr, Wq] per :attr:`query_reply_spec`.  Must be pure and
        traceable (called inside the jitted step) and must NOT mutate
        state — reads never enter the log.  Only called when
        :attr:`query_spec` is not None."""
        raise NotImplementedError

    def encode_query(self, query: Any):
        """Host query -> encoded int row (the read twin of
        :meth:`encode_command`)."""
        raise NotImplementedError

    def decode_query_reply(self, reply_array) -> Any:
        return reply_array

    def jit_apply_batch(self, meta, commands, mask, state):
        """Fold a window of commands at once, order-equivalently to a
        sequential masked jit_apply fold.  commands: [..., A, C];
        mask: bool[..., A] (True = apply); state leading dims match the
        ... prefix.  Returns the new state (per-command replies are not
        part of this path — the engine discards them).  Only called when
        supports_batch_apply is True.  The default is the sequential
        fold; machines override it to add vectorized fast paths."""
        return self.sequential_window_fold(meta, commands, mask, state)

    def window_fold_dispatch(self, meta, commands, mask, state, fast_ok):
        """Shared jit_apply_batch dispatcher for machines with a
        vectorized common-case fold: route to ``self._batch_fast`` when
        ``fast_ok`` (a scalar bool — commonly "no sequential-only op in
        the masked window"), else to the in-order sequential fold.
        Concrete predicates branch in Python (host/eager callers);
        traced ones become a single lax.cond."""
        return cond_concrete(
            fast_ok,
            lambda args: self._batch_fast(*args),
            lambda args: self.sequential_window_fold(meta, *args),
            (commands, mask, state))

    def sequential_window_fold(self, meta, commands, mask, state):
        """Masked in-order lax.scan of jit_apply over the window axis —
        the universal (slow) jit_apply_batch; custom folds use it as
        their fallback branch for windows they cannot vectorize."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        idx = meta["index"]
        # term arrives window-shaped (the engine passes [N,1,1]); give
        # jit_apply the same per-command leading dims as index so a
        # machine reading meta["term"] broadcasts correctly
        term = jnp.broadcast_to(meta["term"], idx.shape)

        def body(mac, xs):
            cmd, do, ix, tm = xs
            new, _reply = self.jit_apply(
                {"index": ix, "term": tm}, cmd, mac)
            merged = jax.tree.map(
                lambda n, o: jnp.where(
                    do.reshape(do.shape + (1,) * (n.ndim - do.ndim)), n, o),
                new, mac)
            return merged, None

        xs = (jnp.moveaxis(commands, -2, 0), jnp.moveaxis(mask, -1, 0),
              jnp.moveaxis(idx, -1, 0), jnp.moveaxis(term, -1, 0))
        final, _ = lax.scan(body, state, xs)
        return final

    def encode_command(self, command: Any):
        raise NotImplementedError

    def decode_reply(self, reply_array) -> Any:
        return reply_array

    # -- host-side protocol so JitMachines also run on the classic path ----

    def init(self, config: dict) -> Any:
        import numpy as np  # local import: host path only
        import jax
        state = self.jit_init(1)
        return jax.tree.map(lambda x: np.asarray(x)[0], state)

    def apply(self, meta: ApplyMeta, command: Any, state: Any):
        import jax.numpy as jnp
        import jax
        # jit once per (class, scalar config): an eager jit_apply
        # re-traces control-flow primitives (lax.fori_loop bodies) on
        # every call, turning each classic-path apply into a fresh
        # compile — and caching per-instance would still compile once
        # per cluster member.  Sound because jit_apply is pure in
        # (meta, command, state) given the config (the class contract
        # above) — but only when the whole config is scalar: a machine
        # holding non-scalar config (arrays, tuples) falls back to a
        # per-instance compile, since two such instances could share
        # every scalar attr yet differ in behavior.
        attrs = [(k, v) for k, v in sorted(self.__dict__.items())
                 if not k.startswith("_")]
        if all(isinstance(v, (int, float, str, bool)) for _k, v in attrs):
            key = (type(self), tuple(attrs))
            fn = _HOST_APPLY_JIT_CACHE.get(key)
        else:
            # non-scalar config: keep the compile on the instance itself
            # (an id()-keyed shared cache could alias a GC'd instance)
            key = None
            fn = self.__dict__.get("_host_apply_jit")
        if fn is None:
            bound = type(self).jit_apply
            inst = self
            fn = jax.jit(lambda m, c, s: bound(inst, m, c, s))
            if key is not None:
                _HOST_APPLY_JIT_CACHE[key] = fn
            else:
                self.__dict__["_host_apply_jit"] = fn
        meta_arr = {"index": jnp.int32(meta.index), "term": jnp.int32(meta.term)}
        enc = self.encode_command(command)
        new_state, reply = fn(meta_arr, enc, state)
        return new_state, self.decode_reply(reply)
