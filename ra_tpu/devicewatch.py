"""Device-plane runtime observatory (ISSUE 16).

The jit-plane static gates are proof-only: RA13 proves no closure
HAZARD can retrace, RA04 proves the dispatch loop ISSUES no blocking
sync, RA14 proves donation is DECLARED — none of them measure what the
runtime actually did.  A silent retrace (a shape-drifting argument), an
unplanned h2d/d2h transfer, or donation quietly not releasing buffers
shows up only as an unexplained throughput cliff.  This module is the
runtime mirror: three cheap host-side instruments behind one
process-wide singleton (``WATCH``, the ``RECORDER`` idiom), surfaced
as the ``device`` Observatory source / ``DEVICE_FIELDS`` registry
group.

**Recompile sentinel** — :meth:`DeviceWatch.wrap_jit` wraps a jitted
callable in a :class:`_SentinelProxy` that watches the pjit cache size
around each call (``_cache_size()`` is a host-side dict ``len``, no
device work).  Cache growth means THIS call compiled: the proxy
attributes the call's wall time to ``compile_ms``, counts a compile
(and a RECOMPILE when it is not the callable's first), and diffs the
triggering call's abstract signature — shape/dtype/sharding per arg
leaf — against the previously compiled one to name WHICH argument
drifted.  The proxy lives in lockstep's ``_STEP_JIT_CACHE`` next to
the jitted fn it wraps, so engines sharing a cache entry share one
compile count.  Steady-state cost per dispatch: one ``time.monotonic``
+ two cache-size reads + an int compare — the <3% overhead pin in
tests/test_devicewatch.py holds the line.  XLA ``cost_analysis()``
(flops / bytes accessed per compiled variant) is gated behind
``cost_enabled`` because ``lower().compile()`` forces a duplicate
compile — a diagnostic, never an always-on tax.

**Transfer ledger** — :func:`record_h2d` / :func:`record_d2h` count
transfer events and bytes per named call site (driver staging, window
readbacks, telemetry harvests, mesh sharding, WAL encode readbacks).
The taps are plain host dict increments on metadata the caller already
holds (``.nbytes``), so they are legal inside RA02/RA04-gated closures
— the ledger turns the "fixed per-window transfer budget" from an RA04
lint promise into a measured number.

**Memory watermarks** — :meth:`DeviceWatch.sample_watermarks` reads
live buffer count/bytes from ``jax.live_arrays()`` (host metadata, no
sync) plus per-device allocator stats where the backend exposes them,
called from the TelemetrySampler's existing harvest tick (zero new
syncs — see docs/INTERNALS.md).  ``peak_live_bytes`` is the high-water
mark; ``buffers_freed`` counts net live-buffer releases observed
between samples — under effective donation the live set stays flat
while dispatches grow, so a monotonically growing live set with zero
frees is the donation-regression signature (RA14's runtime twin).
"""
from __future__ import annotations

import collections
import time
from typing import Any, Optional

from .blackbox import record
from .metrics import DEVICE_FIELDS

__all__ = ["DeviceWatch", "WATCH", "record_h2d", "record_d2h",
           "wrap_jit", "sample_watermarks"]


def _leaf_sig(x: Any) -> tuple:
    """(shape, dtype, sharding) of one arg leaf — metadata only."""
    shape = getattr(x, "shape", None)
    if shape is None:
        return ("py", type(x).__name__, "")
    dtype = getattr(x, "dtype", None)
    sharding = getattr(x, "sharding", None)
    return (str(shape), str(dtype), str(sharding) if sharding else "")


def _abstract_sig(args: tuple, kwargs: dict) -> list:
    """[(path, leaf_sig)] for a call's arguments.  Paths come from
    tree_flatten_with_path so the drift report can say ``args[1].log``
    instead of "leaf 17"."""
    import jax

    try:
        leaves, _ = jax.tree_util.tree_flatten_with_path((args, kwargs))
        return [("".join(str(k) for k in path), _leaf_sig(leaf))
                for path, leaf in leaves]
    except Exception:  # noqa: BLE001 — older tree_util: indexed leaves
        leaves = jax.tree_util.tree_leaves((args, kwargs))
        return [(f"leaf[{i}]", _leaf_sig(leaf))
                for i, leaf in enumerate(leaves)]


def _diff_sig(old: Optional[list], new: list) -> str:
    """Name the first drifting argument between two call signatures."""
    if old is None:
        return "first-compile"
    if len(old) != len(new):
        return (f"arg tree structure changed "
                f"({len(old)} -> {len(new)} leaves)")
    for (opath, osig), (npath, nsig) in zip(old, new):
        if osig != nsig:
            what = ("shape" if osig[0] != nsig[0] else
                    "dtype" if osig[1] != nsig[1] else "sharding")
            return (f"{npath or opath}: {what} {osig} -> {nsig}")
    return "signature-identical retrace (cache eviction?)"


def _new_site() -> dict:
    return {"h2d_events": 0, "h2d_bytes": 0,
            "d2h_events": 0, "d2h_bytes": 0}


def _new_fn_entry() -> dict:
    return {"compiles": 0, "recompiles": 0, "compile_ms": 0.0}


class _SentinelProxy:
    """Callable wrapper counting compiles via pjit cache-size growth.

    Attribute access falls through to the wrapped callable, so
    ``.lower()`` / ``._clear_cache()`` callers are unaffected.  The
    proxy is never passed INTO ``jax.jit`` (it wraps the jitted
    output), so it cannot become a traced closure (RA13-safe by
    construction).
    """

    __slots__ = ("_inner", "_tag", "_watch", "_last_sig", "_seen_sigs",
                 "_compiles")

    def __init__(self, inner, tag: str, watch: "DeviceWatch") -> None:
        self._inner = inner
        self._tag = tag
        self._watch = watch
        self._last_sig: Optional[list] = None
        # per-PROXY compile count: a recompile is the 2nd+ compile of
        # THIS wrapped callable — two different-config engines sharing
        # a tag each get one legitimate warm-up compile
        self._compiles = 0
        # fallback for callables without _cache_size (a plain function
        # or an exotic jit wrapper): track signatures we have seen and
        # call a new one a compile
        self._seen_sigs: Optional[set] = None

    def _cache_size(self) -> Optional[int]:
        try:
            return self._inner._cache_size()
        except Exception:  # noqa: BLE001 — no pjit cache introspection
            return None

    def __call__(self, *args, **kwargs):
        w = self._watch
        if not w.enabled:
            return self._inner(*args, **kwargs)
        before = self._cache_size()
        t0 = time.monotonic()
        out = self._inner(*args, **kwargs)
        if before is not None:
            after = self._cache_size()
            if after is not None and after > before:
                self._note_compile(args, kwargs,
                                   (time.monotonic() - t0) * 1e3)
            return out
        # signature-tracking fallback: costs one abstract-sig walk per
        # call, only on backends without cache introspection
        sig = _abstract_sig(args, kwargs)
        if self._seen_sigs is None:
            self._seen_sigs = set()
        key = tuple(s for _p, s in sig)
        if key not in self._seen_sigs:
            self._seen_sigs.add(key)
            self._note_compile(args, kwargs,
                               (time.monotonic() - t0) * 1e3, sig=sig)
        return out

    def _note_compile(self, args, kwargs, ms: float, sig=None) -> None:
        w = self._watch
        if sig is None:
            sig = _abstract_sig(args, kwargs)
        c = w.counters
        ent = w.per_fn[self._tag]
        self._compiles += 1
        c["compiles"] += 1
        c["compile_ms"] += ms
        ent["compiles"] += 1
        ent["compile_ms"] += ms
        if self._compiles > 1:
            c["recompiles"] += 1
            ent["recompiles"] += 1
            drift = _diff_sig(self._last_sig, sig)
            ent["last_drift"] = drift
            record("device.recompile", fn=self._tag, drift=drift,
                   compile_ms=round(ms, 3))
        self._last_sig = sig
        if w.cost_enabled:
            ent["cost"] = self._cost_analysis(args, kwargs)

    def _cost_analysis(self, args, kwargs) -> dict:
        """flops / bytes-accessed of the just-compiled variant.  Forces
        a DUPLICATE compile via lower().compile() — diagnostic only."""
        try:
            ca = self._inner.lower(*args, **kwargs) \
                .compile().cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            return {"flops": float(ca.get("flops", -1.0)),
                    "bytes_accessed": float(
                        ca.get("bytes accessed",
                               ca.get("bytes_accessed", -1.0)))}
        except Exception:  # noqa: BLE001 — donated inputs / no backend
            return {"flops": -1.0, "bytes_accessed": -1.0}

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def __repr__(self) -> str:
        return f"<sentinel:{self._tag} {self._inner!r}>"


class DeviceWatch:
    """Process-wide device-plane observatory: recompile sentinel +
    transfer ledger + memory watermarks, one ``overview()`` dict."""

    def __init__(self) -> None:
        #: master switch: False = every tap is a no-op pass-through
        #: (the A/B knob of the overhead pin, mirroring
        #: ``RECORDER.enabled``)
        self.enabled = True
        #: opt-in XLA cost_analysis per compiled variant — forces a
        #: duplicate compile per variant, so default-off
        self.cost_enabled = False
        self.counters: dict = {}
        #: tag -> per-wrapped-callable sentinel detail (compiles /
        #: recompiles / compile_ms / last_drift / optional cost)
        self.per_fn: collections.defaultdict = \
            collections.defaultdict(_new_fn_entry)
        #: call-site -> transfer ledger slice; factory keeps dict
        #: allocation OUT of the tap functions (RA08-gated closures
        #: reach them from the mesh ingress wave)
        self.sites: collections.defaultdict = \
            collections.defaultdict(_new_site)
        self._prev_live_buffers: Optional[int] = None
        self._last_census_s = float("-inf")
        self.reset()

    # -- lifecycle --------------------------------------------------------

    def reset(self) -> None:
        """Zero every instrument (tests and bench measured windows)."""
        self.counters = {f: 0 for f in DEVICE_FIELDS}
        self.counters["compile_ms"] = 0.0
        self.per_fn.clear()
        self.sites.clear()
        self._prev_live_buffers = None
        self._last_census_s = float("-inf")

    # -- recompile sentinel -----------------------------------------------

    def wrap_jit(self, jitted, tag: str):
        """Wrap a jitted callable with the recompile sentinel.
        Idempotent: wrapping a proxy returns it unchanged."""
        if isinstance(jitted, _SentinelProxy):
            return jitted
        return _SentinelProxy(jitted, tag, self)

    # -- transfer ledger --------------------------------------------------

    def record_h2d(self, site: str, nbytes: int, events: int = 1) -> None:
        if not self.enabled:
            return
        c = self.counters
        c["h2d_events"] += events
        c["h2d_bytes"] += nbytes
        s = self.sites[site]
        s["h2d_events"] += events
        s["h2d_bytes"] += nbytes

    def record_d2h(self, site: str, nbytes: int, events: int = 1) -> None:
        if not self.enabled:
            return
        c = self.counters
        c["d2h_events"] += events
        c["d2h_bytes"] += nbytes
        s = self.sites[site]
        s["d2h_events"] += events
        s["d2h_bytes"] += nbytes

    # -- memory watermarks ------------------------------------------------

    def sample_watermarks(self, min_interval_s: float = 0.0) -> bool:
        """Live-buffer census, called from the TelemetrySampler harvest
        tick.  ``jax.live_arrays()`` + ``.nbytes`` are host metadata —
        no device sync (the whole point of riding the harvest cadence
        instead of adding one) — but the walk is O(live buffers), so
        harvest callers pass ``min_interval_s`` to cap census frequency
        in buffer-heavy processes; a throttled call returns False
        without sampling."""
        if not self.enabled:
            return False
        if min_interval_s > 0.0 and \
                time.monotonic() - self._last_census_s < min_interval_s:
            return False
        try:
            import jax

            arrs = jax.live_arrays()
            n = len(arrs)
            nbytes = sum(self._safe_nbytes(a) for a in arrs)
        except Exception:  # noqa: BLE001 — backend without live_arrays
            return False
        self._last_census_s = time.monotonic()
        c = self.counters
        c["live_buffers"] = n
        c["live_bytes"] = nbytes
        if nbytes > c["peak_live_bytes"]:
            c["peak_live_bytes"] = nbytes
        prev = self._prev_live_buffers
        if prev is not None and n < prev:
            c["buffers_freed"] += prev - n
        self._prev_live_buffers = n
        c["watermark_samples"] += 1
        return True

    @staticmethod
    def _safe_nbytes(a) -> int:
        try:
            return int(a.nbytes)
        except Exception:  # noqa: BLE001 — deleted/donated buffer
            return 0

    def device_memory_stats(self) -> dict:
        """Per-device allocator stats where the backend exposes them
        (TPU/GPU ``memory_stats()``; None on CPU) — diagnostic surface
        for ra_top's ``--once`` deep dive, not part of the sampled
        counter set."""
        out: dict = {}
        try:
            import jax

            for d in jax.local_devices():
                stats = None
                try:
                    stats = d.memory_stats()
                except Exception:  # noqa: BLE001 — CPU backend
                    stats = None
                if stats:
                    out[str(d.id)] = {
                        "bytes_in_use": int(stats.get("bytes_in_use", -1)),
                        "peak_bytes_in_use": int(
                            stats.get("peak_bytes_in_use", -1)),
                    }
        except Exception:  # noqa: BLE001 — no jax at all
            pass
        return out

    # -- surface ----------------------------------------------------------

    def overview(self) -> dict:
        """The ``device`` Observatory source: flat DEVICE_FIELDS
        counters plus nested per-callable sentinel detail and the
        per-site transfer ledger (the Observatory flattens nesting
        into ``device_per_fn_<tag>_<field>`` ring keys)."""
        snap = dict(self.counters)
        snap["per_fn"] = {
            tag: {k: v for k, v in ent.items() if k != "cost"}
            for tag, ent in self.per_fn.items()}
        for tag, ent in self.per_fn.items():
            cost = ent.get("cost")
            if cost:
                snap["per_fn"][tag].update(
                    {f"cost_{k}": v for k, v in cost.items()})
        snap["sites"] = {site: dict(s) for site, s in self.sites.items()}
        return snap


#: the process-wide device watch (the RECORDER idiom): importers call
#: the module-level taps so instrumentation sites stay one line
WATCH = DeviceWatch()


def wrap_jit(jitted, tag: str):
    return WATCH.wrap_jit(jitted, tag)


def record_h2d(site: str, nbytes: int, events: int = 1) -> None:
    WATCH.record_h2d(site, nbytes, events)


def record_d2h(site: str, nbytes: int, events: int = 1) -> None:
    WATCH.record_d2h(site, nbytes, events)


def sample_watermarks(min_interval_s: float = 0.0) -> bool:
    return WATCH.sample_watermarks(min_interval_s)


def bench_tail_keys(commands: Optional[int] = None) -> dict:
    """The device-plane bench/soak JSON-tail stamp (ISSUE 16): ONE
    definition of the keys tools/bench_diff.py compares —
    ``n_compiles`` (must not grow round-over-round), ``compile_time_s``,
    ``transfer_bytes`` (+ ``transfer_bytes_per_cmd`` when the caller
    passes its command count), ``peak_live_bytes``.  Values are
    process-lifetime totals: warm-up compiles are part of a round's
    compile budget, and a round-over-round n_compiles GROWTH is
    exactly the retrace regression the diff flags."""
    c = WATCH.counters
    out = {
        "n_compiles": c["compiles"],
        "n_recompiles": c["recompiles"],
        "compile_time_s": round(c["compile_ms"] / 1e3, 6),
        "transfer_bytes": c["h2d_bytes"] + c["d2h_bytes"],
        "peak_live_bytes": c["peak_live_bytes"],
    }
    if commands:
        out["transfer_bytes_per_cmd"] = round(
            out["transfer_bytes"] / commands, 4)
    return out
