"""Machine registry — named, picklable machine specifications.

The reference starts servers on REMOTE nodes by shipping a config whose
machine is a module name + args (plain atoms/terms over rpc:call,
ra_server_sup_sup.erl:42-130) and recovers the same config from the
target's disk on restart.  ra_tpu machines are Python objects, so the
cross-node equivalent is a **spec**: ``("$machine", name, kwargs)``
resolved against a process-local registry on the node that actually
constructs the server.  Specs are picklable, travel over the TCP
control plane, and persist in the directory's config snapshot so a
remote restart can rebuild the machine from disk alone
(recover_config, ra_server_sup_sup.erl:80-103).

Register custom machines at import time on every node process::

    from ra_tpu.machines import register_machine
    register_machine("my_queue", lambda **kw: MyQueueMachine(**kw))

Built-in models are pre-registered: fifo, jit_fifo, jit_kv, registers,
counter (an integer-adding SimpleMachine).
"""
from __future__ import annotations

from typing import Any, Callable, Optional

_REGISTRY: dict = {}

SPEC_TAG = "$machine"


def register_machine(name: str, factory: Callable[..., Any]) -> None:
    """Register ``factory(**kwargs) -> Machine`` under ``name``."""
    _REGISTRY[name] = factory


def machine_spec(name: str, **kwargs: Any) -> tuple:
    """A picklable machine description for cross-node start/restart."""
    return (SPEC_TAG, name, kwargs)


def is_machine_spec(obj: Any) -> bool:
    return (isinstance(obj, tuple) and len(obj) == 3 and
            obj[0] == SPEC_TAG and isinstance(obj[1], str) and
            isinstance(obj[2], dict))


def resolve_machine(spec: Any):
    """Build the machine named by ``spec`` (idempotent on Machine
    instances so local callers can pass either).  The resolved machine
    remembers its spec (``_machine_spec``) so config snapshots persist
    it for disk-based recovery."""
    from .core.machine import Machine

    if isinstance(spec, Machine):
        return spec
    if not is_machine_spec(spec):
        raise ValueError(f"not a machine spec: {spec!r}")
    _tag, name, kwargs = spec
    factory = _REGISTRY.get(name)
    if factory is None:
        raise KeyError(f"machine {name!r} is not registered on this node "
                       f"(known: {sorted(_REGISTRY)})")
    machine = factory(**kwargs)
    machine._machine_spec = (SPEC_TAG, name, dict(kwargs))
    return machine


def spec_of(machine: Any) -> Optional[tuple]:
    """The spec a machine was resolved from, if any — what the config
    snapshot persists for remote/disk recovery."""
    return getattr(machine, "_machine_spec", None)


def _register_builtins() -> None:
    def counter(initial: int = 0):
        from .core.machine import SimpleMachine
        return SimpleMachine(lambda c, s: s + c, initial)

    def fifo(**kw):
        from .models import FifoMachine
        return FifoMachine(**kw)

    def jit_fifo(**kw):
        from .models import JitFifoMachine
        return JitFifoMachine(**kw)

    def jit_kv(**kw):
        from .models import JitKvMachine
        return JitKvMachine(**kw)

    def registers(**kw):
        from .models import RegisterMachine
        return RegisterMachine(**kw)

    register_machine("counter", counter)
    register_machine("fifo", fifo)
    register_machine("jit_fifo", jit_fifo)
    register_machine("jit_kv", jit_kv)
    register_machine("registers", registers)


_register_builtins()
