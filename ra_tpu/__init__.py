"""ra-tpu: a TPU-native multi-Raft consensus framework.

Brand-new implementation with the capabilities of RabbitMQ Ra
(reference at /root/reference, studied — not ported): thousands of
co-hosted Raft clusters whose hot vote/commit arithmetic is evaluated as
batched XLA kernels, with a pure host-side core as the oracle and the
handler of rare/divergent transitions.
"""

__version__ = "0.1.0"
