"""ra-tpu: a TPU-native multi-Raft consensus framework.

Brand-new implementation with the capabilities of RabbitMQ Ra
(reference at /root/reference, studied — not ported): thousands of
co-hosted Raft clusters whose hot vote/commit arithmetic is evaluated as
batched XLA kernels, with a pure host-side core as the oracle and the
handler of rare/divergent transitions.
"""

__version__ = "0.1.0"

from .api import (  # noqa: E402,F401
    add_member,
    aux_command,
    cast_aux_command,
    consistent_query,
    delete_cluster,
    force_delete_server,
    force_shrink_members_to_current_member,
    key_metrics,
    leader_query,
    local_query,
    member_overview,
    members,
    members_info,
    new_uid,
    node_call,
    overview,
    ping,
    pipeline_command,
    pipeline_commands,
    process_command,
    remove_member,
    restart_server,
    start_cluster,
    start_server,
    stop_server,
    transfer_leadership,
    trigger_election,
)
from .core import aux  # noqa: E402,F401
from .directory import Directory  # noqa: E402,F401
from .node import LocalRouter, RaNode  # noqa: E402,F401
from .system import RaSystem  # noqa: E402,F401
