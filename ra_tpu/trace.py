"""Tracing / profiling hooks (SURVEY.md §5 "tracing/profiling").

The reference keeps profiling out of the hot path: a swappable logger in
persistent_term (ra.hrl:206-228) plus commented-out looking_glass flame
hooks in ra_bench (ra_bench.erl:199-212).  This module is the tpu-native
equivalent, with the same always-off-by-default contract:

* a process-wide swappable :class:`Tracer` (``set_tracer`` /
  ``get_tracer``) — the persistent_term '$ra_logger' pattern;
* span recording into a bounded in-memory buffer, dumped as Chrome
  trace-event JSON (chrome://tracing / perfetto load it directly) —
  the flame-graph role of the lg hooks;
* :func:`jax_profile`, wrapping ``jax.profiler.trace`` so a bench run
  can capture an XLA/TPU timeline (the device-side callgrind);
* when no tracer is installed the instrumentation cost is one module
  attribute read + an ``is None`` test per site.

Instrumented sites: the lane engine's step dispatch / durability bridge
(ra_tpu.engine), the WAL batch loop (ra_tpu.log.wal), and anything user
code wraps via ``trace.span``.
"""
from __future__ import annotations

import contextlib
import itertools
import json
import os
import threading
import time
from typing import Any, Iterator, Optional

#: the installed tracer, or None (tracing disabled).  Module attribute on
#: purpose: instrumented call sites read it once per operation.
_tracer: Optional["Tracer"] = None


# -- causal trace context (ISSUE 7) ------------------------------------------
#
# Every classic-path command gets a trace id at ingress (api.py /
# FifoClient / reliable RPC).  Ids are DETERMINISTIC given the run: a
# process-wide counter under a settable origin prefix, so a seeded soak
# replays the same ids (set_trace_origin("soak42")) while the default
# prefix keeps ids unique across cooperating processes.  The context is
# a plain short string — it rides command objects, RPC frames and
# pickles untouched, and flight-recorder events join on it
# (ra_tpu.blackbox / tools/ra_trace.py).

_trace_seq = itertools.count(1)
_trace_origin = f"p{os.getpid()}"


def set_trace_origin(origin: str) -> None:
    """Set the trace-id prefix AND restart the sequence — the knob a
    seeded run uses to make its command trace ids reproducible."""
    global _trace_seq, _trace_origin
    _trace_origin = str(origin)
    _trace_seq = itertools.count(1)


def new_trace_ctx(origin: Optional[str] = None) -> str:
    """Mint one trace context: ``<origin>-<seq>``."""
    return f"{origin or _trace_origin}-{next(_trace_seq)}"


def set_tracer(tracer: Optional["Tracer"]) -> None:
    """Install (or, with None, remove) the process-wide tracer."""
    global _tracer
    _tracer = tracer


def get_tracer() -> Optional["Tracer"]:
    return _tracer


class Tracer:
    """Bounded in-memory span/counter recorder.

    Spans nest freely across threads (thread id becomes the Chrome
    ``tid``); the buffer is a ring of ``capacity`` events — tracing a
    long bench keeps the newest events instead of growing unboundedly.
    """

    def __init__(self, capacity: int = 200_000) -> None:
        self.capacity = capacity
        self._events: list = []
        self._head = 0          # ring cursor once the buffer is full
        self._dropped = 0       # events overwritten after the ring wrapped
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()

    # -- recording ---------------------------------------------------------

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def _push(self, evt: dict) -> None:
        with self._lock:
            if len(self._events) < self.capacity:
                self._events.append(evt)
            else:
                self._events[self._head] = evt
                self._head = (self._head + 1) % self.capacity
                self._dropped += 1

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "ra", **args: Any) -> Iterator[None]:
        """Record a complete ("ph":"X") span around the with-body."""
        start = self._now_us()
        try:
            yield
        finally:
            self._push({"name": name, "cat": cat, "ph": "X",
                        "ts": start, "dur": self._now_us() - start,
                        "pid": os.getpid(),
                        "tid": threading.get_ident() & 0xFFFF,
                        **({"args": args} if args else {})})

    def instant(self, name: str, cat: str = "ra", **args: Any) -> None:
        self._push({"name": name, "cat": cat, "ph": "i", "s": "t",
                    "ts": self._now_us(), "pid": os.getpid(),
                    "tid": threading.get_ident() & 0xFFFF,
                    **({"args": args} if args else {})})

    def counter(self, name: str, **values: float) -> None:
        self._push({"name": name, "ph": "C", "ts": self._now_us(),
                    "pid": os.getpid(), "tid": 0, "args": values})

    # -- readout -----------------------------------------------------------

    def events(self) -> list:
        with self._lock:
            if len(self._events) < self.capacity:
                return list(self._events)
            return (self._events[self._head:] + self._events[:self._head])

    @property
    def wrapped(self) -> bool:
        """True once the ring has overwritten at least one event —
        the buffer no longer holds the full history."""
        return self._dropped > 0

    @property
    def dropped_events(self) -> int:
        return self._dropped

    def dump_chrome_trace(self, path: str) -> str:
        """Write the buffer as Chrome trace-event JSON (atomic replace);
        load in chrome://tracing or ui.perfetto.dev."""
        payload = {"traceEvents": self.events(),
                   "displayTimeUnit": "ms"}
        tmp = path + ".partial"
        with open(tmp, "w") as f:
            json.dump(payload, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path

    def summary(self) -> dict:
        """Per-span-name {count, total_us, max_us} rollup — the quick
        console profile when a full timeline is overkill.  The ``_meta``
        entry reports whether the ring wrapped (``wrapped: True`` +
        ``dropped_events``): a truncated trace's counts cover only the
        newest ``capacity`` events and must not be read as totals."""
        out: dict[str, dict] = {}
        for e in self.events():
            if e.get("ph") != "X":
                continue
            s = out.setdefault(e["name"],
                               {"count": 0, "total_us": 0.0, "max_us": 0.0})
            s["count"] += 1
            s["total_us"] += e["dur"]
            s["max_us"] = max(s["max_us"], e["dur"])
        out["_meta"] = {"wrapped": self.wrapped,
                        "dropped_events": self._dropped}
        return out


# -- zero-overhead instrumentation helper -----------------------------------

#: shared no-op context (nullcontext is documented reentrant+reusable):
#: the disabled path allocates nothing per call
_NULL = contextlib.nullcontext()


def span(name: str, cat: str = "ra", **args: Any):
    """Span against the installed tracer, or a shared no-op context when
    tracing is off (one attribute read + None test + the call itself)."""
    t = _tracer
    if t is None:
        return _NULL
    return t.span(name, cat, **args)


def instant(name: str, cat: str = "ra", **args: Any) -> None:
    t = _tracer
    if t is not None:
        t.instant(name, cat, **args)


# -- device-side profiling ---------------------------------------------------

@contextlib.contextmanager
def jax_profile(log_dir: str) -> Iterator[None]:
    """Capture an XLA profiler trace (TensorBoard/XProf format) around
    the with-body — the device-timeline analogue of the reference's
    looking_glass hooks (ra_bench.erl:199-212).  Requires a live jax
    backend; safe to nest around engine steps.

    The capture is stamped into the flight recorder on exit
    (``profile.captured`` + the profile dir), so a bench-time capture
    shows up in ra_trace timelines next to the events it covers
    instead of being a side file nobody finds (ISSUE 16)."""
    import jax

    from .blackbox import record

    t0 = time.perf_counter()
    with jax.profiler.trace(log_dir):
        yield
    record("profile.captured", dir=str(log_dir),
           wall_s=round(time.perf_counter() - t0, 3))
