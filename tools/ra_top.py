"""ra_top — live terminal view of the Observatory (ISSUE 6).

Follows a JSONL snapshot ring (what ``tools/soak.py --obs`` or
``Observatory.to_jsonl`` writes) and renders the lane-health heat
summary, the top-K offender lanes, per-shard WAL fsync latency + queue
depth, the dispatch-pipeline counters, and the device plane (ISSUE 16:
compiles/recompiles, transfer ledger, memory watermarks).  stdlib-only,
works over ssh; the htop role of the reference's `ra:key_metrics`
console habit.

Usage:
    python tools/ra_top.py [path] [--interval S] [--once]

``path`` defaults to ``obs.jsonl`` in the cwd.  ``--once`` prints a
single frame without clearing the screen (what the tests drive; also
handy for cron/log capture).
"""
from __future__ import annotations

import json
import sys
import time

#: log2 histogram sparkline glyphs, low->high occupancy
_BARS = " .:-=+*#%@"


def _read_tail(path: str, n: int = 2) -> list:
    """Newest n parsable snapshots (oldest first); torn-tail tolerant."""
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError:
        return []
    out = []
    for raw in lines[-(n + 1):]:
        try:
            out.append(json.loads(raw))
        except ValueError:
            continue
    return out[-n:]


def _spark(hist: list) -> str:
    top = max(hist) if hist else 0
    if top <= 0:
        return _BARS[0] * len(hist)
    return "".join(
        _BARS[min(len(_BARS) - 1, int(v / top * (len(_BARS) - 1) + 0.999))]
        for v in hist)


def _fmt_rate(v: float) -> str:
    for div, suf in ((1e9, "G"), (1e6, "M"), (1e3, "k")):
        if abs(v) >= div:
            return f"{v / div:.2f}{suf}"
    return f"{v:.1f}"


def render(snap: dict, prev: dict | None = None) -> str:
    """One frame of the dashboard as plain text."""
    lines: list = []
    eng = snap.get("engine", {})
    tel = eng.get("telemetry") or {}
    pipe = eng.get("pipeline", {})
    sampler = eng.get("sampler", {})
    ts = snap.get("ts", 0.0)
    lines.append(
        f"ra_top  seq={snap.get('seq', '?')}  "
        f"{time.strftime('%H:%M:%S', time.localtime(ts))}  "
        f"lanes={eng.get('lanes', '?')}x{eng.get('members', '?')}")
    # -- commit rate over the last window ---------------------------------
    if prev is not None:
        p_tel = prev.get("engine", {}).get("telemetry") or {}
        dt = max(ts - prev.get("ts", ts), 1e-9)
        # commit rate over the SAMPLER's own window: a JSONL export
        # faster than the harvest cadence re-embeds the same sample,
        # and the snapshot-ts delta would then read a running engine
        # as 0 cmds/s; "--" = no fresh sample between these snapshots
        dt_tel = (tel.get("ts", 0.0) - p_tel.get("ts", 0.0)
                  if tel.get("ts") and p_tel.get("ts") else 0.0)
        dc = (tel.get("committed_total", 0.0)
              - p_tel.get("committed_total", 0.0))
        cmds = _fmt_rate(dc / dt_tel) if dt_tel > 1e-9 else "--"
        di = (pipe.get("inner_steps", 0)
              - prev.get("engine", {}).get("pipeline", {})
              .get("inner_steps", 0))
        lines.append(f"rate    {cmds} cmds/s   "
                     f"{_fmt_rate(di / dt)} steps/s   window {dt:.1f}s")
    # -- lane health -------------------------------------------------------
    if tel:
        stalled = tel.get("stalled_lanes", 0)
        flag = " <<< STALLED LANES" if stalled else ""
        lines.append(
            f"lanes   stalled={stalled}{flag}  "
            f"commit_lag max={tel.get('commit_lag_max', 0)} "
            f"mean={tel.get('commit_lag_mean', 0)}  "
            f"apply_lag max={tel.get('apply_lag_max', 0)}  "
            f"leader_age_min={tel.get('leader_age_min', 0)}")
        hist = tel.get("commit_lag_hist")
        if hist:
            lines.append(f"lag     [{_spark(hist)}]  log2 buckets "
                         f"0..2^{len(hist) - 1}  n={sum(hist)}")
        top = tel.get("top_lanes") or []
        if top:
            rows = []
            for r, lane in enumerate(top[:8]):
                cl = (tel.get("top_commit_lag") or [0] * len(top))[r]
                st = (tel.get("top_stall_steps") or [0] * len(top))[r]
                if cl == 0 and st == 0:
                    continue
                rows.append(f"#{lane}(lag={cl},stall={st})")
            lines.append("top     " + (" ".join(rows) if rows
                                       else "(all lanes healthy)"))
    elif "telemetry" not in eng:
        lines.append("lanes   (no telemetry sampler attached)")
    if sampler:
        lines.append(
            f"sampler started={sampler.get('samples_started', 0)} "
            f"harvested={sampler.get('samples_harvested', 0)} "
            f"dropped={sampler.get('samples_dropped', 0)} "
            f"blocking_waits={sampler.get('blocking_waits', 0)}")
    # -- dispatch pipeline -------------------------------------------------
    if pipe:
        disp = pipe.get("dispatches", 0)
        inner = pipe.get("inner_steps", 0)
        fusion = f"{inner / disp:.1f}x" if disp else "-"
        lines.append(
            f"pipe    dispatches={disp} inner_steps={inner} "
            f"fusion={fusion} "
            f"in_flight={pipe.get('dispatches_in_flight', 0)} "
            f"window_syncs={pipe.get('window_syncs', 0)}")
    # -- ingress plane (ISSUE 10) ------------------------------------------
    ing = snap.get("ingress") or {}
    if ing:
        if prev is not None:
            p_ing = prev.get("ingress") or {}
            dt = max(ts - prev.get("ts", ts), 1e-9)
            da = ing.get("accepted", 0) - p_ing.get("accepted", 0)
            rate = _fmt_rate(da / dt)
        else:
            rate = "--"
        shed = ing.get("shed_rows", 0)
        flag = " <<< SHEDDING" if shed and prev is not None and \
            shed > (prev.get("ingress") or {}).get("shed_rows", 0) else ""
        # the durability half of the backlog under durable/mesh runs
        # (ingress queue + unconfirmed WAL steps = uncommitted total)
        wp = ing.get("wal_pending_steps")
        wp_s = f" wal_pending={wp}" if wp is not None else ""
        lines.append(
            f"ingress {rate} acc/s  sessions={ing.get('sessions', 0)} "
            f"q={ing.get('queue_rows', 0)} "
            f"level={ing.get('ladder', {}).get('level_name', '?')} "
            f"dup={ing.get('dup_dropped', 0)} shed={shed}"
            f" rej={ing.get('rejected', 0)}{wp_s}{flag}")
    # -- wire plane (ISSUE 12) ---------------------------------------------
    wire = snap.get("wire") or {}
    if wire:
        p_wire = (prev.get("wire") or {}) if prev is not None else {}
        if prev is not None:
            dt = max(ts - prev.get("ts", ts), 1e-9)
            dr = wire.get("swept_rows", 0) - p_wire.get("swept_rows", 0)
            rate = _fmt_rate(dr / dt)
        else:
            rate = "--"
        # credit-level histogram over the window (falls back to the
        # lifetime totals on the first frame)
        levels = ("credit_ok", "credit_slow", "credit_defer",
                  "credit_reject", "credit_dup", "credit_shed")
        hist = [max(0, wire.get(k, 0) - p_wire.get(k, 0)) for k in levels] \
            if prev is not None else [wire.get(k, 0) for k in levels]
        names = ("ok", "slow", "defer", "rej", "dup", "shed")
        hist_s = " ".join(f"{n}={v}" for n, v in zip(names, hist) if v)
        errs = wire.get("protocol_errors", 0)
        lines.append(
            f"wire    {rate} rec/s  conns={wire.get('conns', 0)} "
            f"(sock={wire.get('socket_conns', 0)} "
            f"paused={wire.get('paused_conns', 0)})  "
            f"credit[{_spark(hist)}] {hist_s or 'idle'}"
            + (f"  errs={errs}" if errs else ""))
    # -- read plane (ISSUE 20) ---------------------------------------------
    rd = snap.get("read") or {}
    if rd:
        p_rd = (prev.get("read") or {}) if prev is not None else {}
        if prev is not None:
            dt = max(ts - prev.get("ts", ts), 1e-9)
            ds = rd.get("served", 0) - p_rd.get("served", 0)
            rate = _fmt_rate(ds / dt)
        else:
            rate = "--"
        # read_p99 from the phase attribution (the read_p99_ms SLO's
        # own signal); -1.0 is the repo-wide "never measured" sentinel
        p99 = ((eng.get("phases") or {}).get("read_e2e") or {}) \
            .get("p99_ms", -1.0)
        p99_s = f"{p99:.1f}ms" if p99 >= 0 else "--"
        stale = rd.get("stale_refused", 0)
        flag = " <<< REFUSING" if prev is not None and \
            stale > p_rd.get("stale_refused", 0) else ""
        shed = rd.get("shed", 0)
        lines.append(
            f"reads   {rate} srv/s  p99={p99_s}  "
            f"lease={rd.get('lease_coverage_pct', 0.0):.0f}%  "
            f"q={rd.get('queue_rows', 0)} shed={shed} "
            f"stale_refused={stale}{flag}")
    # -- device plane (ISSUE 16) -------------------------------------------
    dev = snap.get("device") or {}
    if dev:
        p_dev = (prev.get("device") or {}) if prev is not None else {}
        dre = dev.get("recompiles", 0)
        # <<< flag only on fresh recompiles (like the SHEDDING flag);
        # the drift attribution line sticks around once any recompile
        # happened — naming the drifting argument is the sentinel's job
        flag = " <<< RECOMPILING" \
            if prev is not None and dre > p_dev.get("recompiles", 0) else ""
        drift = ""
        if dre:
            for tag, ent in sorted((dev.get("per_fn") or {}).items()):
                if ent.get("last_drift"):
                    drift = f"\ndrift   {tag}: {ent['last_drift'][:68]}"
                    break
        lines.append(
            f"device  compiles={dev.get('compiles', 0)} re={dre} "
            f"{dev.get('compile_ms', 0.0):.0f}ms  "
            f"h2d={dev.get('h2d_events', 0)}/"
            f"{_fmt_rate(dev.get('h2d_bytes', 0))}B "
            f"d2h={dev.get('d2h_events', 0)}/"
            f"{_fmt_rate(dev.get('d2h_bytes', 0))}B  "
            f"live={dev.get('live_buffers', 0)}/"
            f"{_fmt_rate(dev.get('live_bytes', 0))}B "
            f"peak={_fmt_rate(dev.get('peak_live_bytes', 0))}B "
            f"freed={dev.get('buffers_freed', 0)}{flag}{drift}")
    # -- WAL shards --------------------------------------------------------
    wal = eng.get("wal") or {}
    shards = wal.get("shards") or []
    sys_wal = snap.get("system", {}).get("counters", {}).get("wal")
    if not shards and sys_wal:
        shards = [sys_wal]
    for sh in shards[:8]:
        sid = sh.get("shard", "-")
        lanes_sl = sh.get("lanes")
        lane_s = f" lanes={lanes_sl[0]}..{lanes_sl[1]}" \
            if isinstance(lanes_sl, list) and len(lanes_sl) == 2 else ""
        lines.append(
            f"wal[{sid}] fsync p50={sh.get('fsync_p50_ms', -1)}ms "
            f"p99={sh.get('fsync_p99_ms', -1)}ms "
            f"rec/fsync={sh.get('records_per_fsync', -1)} "
            f"queue={sh.get('queue_depth', 0)} "
            f"jobs={sh.get('jobs_pending', 0)} "
            f"lag={sh.get('confirm_lag_steps', 0)}{lane_s}")
    if len(shards) > 8:
        # a wide per-device mesh layout (one shard per lane device):
        # summarize the tail rather than silently truncating it
        rest = shards[8:]
        worst = max((s.get("fsync_p99_ms", -1) for s in rest),
                    default=-1)
        lag = max((s.get("confirm_lag_steps", 0) for s in rest),
                  default=0)
        jobs = sum(s.get("jobs_pending", 0) for s in rest)
        lines.append(f"wal[+{len(rest)}] worst fsync p99={worst}ms "
                     f"jobs={jobs} lag_max={lag}")
    df = (wal.get("disk_faults")
          or snap.get("system", {}).get("counters", {}).get("disk_faults"))
    if df and any(df.values()):
        hot = " ".join(f"{k}={v}" for k, v in sorted(df.items()) if v)
        lines.append(f"faults  {hot}")
    # -- SLO verdicts (ISSUE 9) --------------------------------------------
    slo = (snap.get("slo") or {}).get("objectives") or {}
    if slo:
        cells = []
        for name in sorted(slo):
            o = slo[name]
            verdict = o.get("verdict", "?")
            mark = {"ok": "OK", "no_data": "--",
                    "breach": "BREACH", "alert": "ALERT!"}.get(
                        verdict, verdict)
            val = o.get("value")
            val_s = "--" if val is None else f"{val:g}"
            cells.append(f"{name} {mark} {val_s}{o.get('op', '')}"
                         f"{o.get('threshold', '')} "
                         f"burn={o.get('burn_fast', 0):g}/"
                         f"{o.get('burn_slow', 0):g}")
        lines.append("slo     " + " | ".join(cells))
    # -- autotuner footer (ISSUE 9): the last decision + freeze state ------
    tun = snap.get("autotune") or {}
    if tun:
        knobs = tun.get("knobs") or {}
        knob_s = " ".join(f"{k}={v:g}" if isinstance(v, float)
                          else f"{k}={v}" for k, v in sorted(knobs.items()))
        last = tun.get("last_decision")
        if last:
            age = max(0.0, ts - last.get("ts", ts))
            dec = (f"{last.get('knob', '?')} {last.get('old', '?')}->"
                   f"{last.get('new', '?')} via {last.get('phase', '?')}"
                   f"/{last.get('objective', '?')} {age:.0f}s ago")
        else:
            dec = "no decisions"
        frozen = f" FROZEN({tun.get('freeze_reason')})" \
            if tun.get("frozen") else ""
        lines.append(f"tuner   {dec}{frozen}")
        lines.append(f"knobs   {knob_s}  decisions="
                     f"{tun.get('decisions', 0)} "
                     f"cooldown={tun.get('cooldown_left', 0)}")
    # -- counters self-metric ---------------------------------------------
    dropped = snap.get("counters", {}).get("self", {}) \
        .get("telemetry_dropped")
    if dropped:
        lines.append(f"WARN    telemetry_dropped={dropped} "
                     "(instrumentation/registry mismatch)")
    # -- last incident (flight recorder, ISSUE 7): a stalled soak must
    # be explainable from the live view — what escalated, where, when,
    # and which bundle to feed tools/ra_trace.py
    inc = (snap.get("blackbox") or {}).get("last_incident")
    if inc:
        age = max(0.0, ts - inc.get("ts", ts))
        bundle = inc.get("path") or ""
        bundle = bundle.rsplit("/", 1)[-1]
        lines.append(
            f"incident {inc.get('reason', '?')} @ "
            f"{inc.get('where', '?')}  {age:.0f}s ago  "
            f"{(inc.get('what') or '')[:36]}  bundle={bundle}")
    return "\n".join(lines)


def main(argv: list) -> int:
    once = "--once" in argv
    interval = 1.0
    args: list = []
    it = iter(argv)
    for a in it:
        if a == "--interval":
            # consume the interval's VALUE operand too, or it would be
            # mistaken for the snapshot path ("ra_top --interval 2")
            interval = float(next(it, "1.0"))
        elif not a.startswith("--"):
            args.append(a)
    path = args[0] if args else "obs.jsonl"
    if once:
        tail = _read_tail(path, 2)
        if not tail:
            print(f"ra_top: no snapshots at {path}")
            return 1
        print(render(tail[-1], tail[-2] if len(tail) > 1 else None))
        return 0
    try:
        while True:
            tail = _read_tail(path, 2)
            frame = render(tail[-1], tail[-2] if len(tail) > 1 else None) \
                if tail else f"ra_top: waiting for snapshots at {path} ..."
            # ANSI home+clear-below: repaint without scrollback spam
            sys.stdout.write("\x1b[H\x1b[J" + frame + "\n")
            sys.stdout.flush()
            time.sleep(interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
