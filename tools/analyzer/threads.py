"""RA12 — thread-role / device-sync checker (ISSUE 14 tentpole part 3).

Classifies functions by EXECUTING THREAD from spawn sites
(``threading.Thread(target=...)`` — the WAL batch/encode workers,
supervisors, TCP reader/sender/detector loops, the wire selector
reader), computes each worker root's cross-module transitive call
closure, and forbids device-touching operations inside it:

* ``jax.*`` / ``jnp.*`` / ``lax.*`` calls — any jax API call from a
  worker thread can compile+enqueue device work, and a multi-device
  enqueue off the dispatch thread DEADLOCKS against an in-flight pjit
  (the PR 11 mesh hang: a WAL encode worker sliced a sharded array)
* bare ``device_put(...)``
* ``.block_until_ready()`` — a worker blocking on device state couples
  worker liveness to the dispatch pipeline

The sanctioned escape is host materialization: the dispatch thread (or
a single designated point, e.g. ``EngineDurability._host_aux``) pulls
device values to host ONCE, workers slice numpy.  A deliberate
worker-side device op carries ``# ra12-ok: <why>`` naming why its
inputs are host-materialized / why no concurrent dispatch can be in
flight.

``np.asarray(...)`` and ``.copy_to_host_async()`` are NOT flagged:
pure d2h transfers of ready values are the idiom the rule steers
toward (documented readback points; RA02 governs those on the
dispatch side).

Scope: package code only (a directory with ``__init__.py``), tests
exempt — test harnesses drive engines from ad-hoc threads on purpose,
and the bench/tools CLIs own their whole process.
"""
from __future__ import annotations

import ast

from .index import root_name as _root_name
from .rules import Finding

__all__ = ["evaluate_thread_roles"]

_DEVICE_MODULES = frozenset({"jax", "jnp", "lax"})


def _is_thread_ctor(call):
    fn = call.func
    if isinstance(fn, ast.Attribute) and fn.attr == "Thread" and \
            isinstance(fn.value, ast.Name) and \
            fn.value.id == "threading":
        return True
    if isinstance(fn, ast.Name) and fn.id == "Thread":
        return True
    return False


def _spawn_targets(idx, fi):
    """(target FuncInfo, spawn lineno) for every Thread(...) spawned
    inside ``fi``."""
    out = []
    for sub in ast.walk(fi.node):
        if not (isinstance(sub, ast.Call) and _is_thread_ctor(sub)):
            continue
        target = None
        for kw in sub.keywords:
            if kw.arg == "target":
                target = kw.value
        if target is None and len(sub.args) >= 2:
            # positional stdlib form: Thread(group, target, ...) — the
            # FIRST positional is `group` (review finding: reading
            # args[0] let positional spawns escape the gate)
            target = sub.args[1]
        elif target is None and len(sub.args) == 1 and not (
                isinstance(sub.args[0], ast.Constant)
                and sub.args[0].value is None):
            # lenient: Thread(worker) is invalid stdlib (group must be
            # None) but clearly MEANS a target — gate it anyway
            target = sub.args[0]
        if target is None:
            continue
        if isinstance(target, ast.Name):
            got = idx.resolve_name(fi.module, target.id)
            if got and got[0] == "func":
                out.append((got[1], sub.lineno))
            else:
                for d in fi.module.func_defs.get(target.id, []):
                    out.append((d, sub.lineno))
        elif isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self" and fi.cls is not None:
            m = idx.find_method(fi.cls, target.attr)
            if m is not None:
                out.append((m, sub.lineno))
    return out


def evaluate_thread_roles(idx):
    """RAW RA12 findings: device-touching ops reachable from worker-
    thread spawn targets."""
    # harvest spawn sites from every indexed package module, not just
    # lint targets — scoped runs evaluate the whole program (see
    # rules._rule_roots)
    roots = []       # (FuncInfo, "file:line" spawn origin, spawn path)
    for mod in idx.by_path.values():
        if mod.in_tests or not mod.in_package:
            continue
        for defs in mod.func_defs.values():
            for fi in defs:
                for target, line in _spawn_targets(idx, fi):
                    roots.append((target, f"{mod.stem}.py:{line}",
                                  mod.path))
    if not roots:
        return []
    # closure, remembering the first spawn origin that reaches a func
    origin = {}
    queue = list(roots)
    closure = {}
    while queue:
        fi, org, opath = queue.pop(0)
        if id(fi) in closure:
            continue
        closure[id(fi)] = fi
        origin[id(fi)] = (org, opath)
        for callee in idx.callees(fi):
            queue.append((callee, org, opath))
    out = []
    for fi in closure.values():
        mod = fi.module
        if mod.in_tests or not mod.in_package:
            continue
        org, opath = origin[id(fi)]
        for sub in ast.walk(fi.node):
            if not isinstance(sub, ast.Call):
                continue
            fn = sub.func
            if isinstance(fn, ast.Attribute):
                root = _root_name(fn)
                if root in _DEVICE_MODULES:
                    out.append(Finding(
                        mod.path, sub.lineno, "RA12",
                        f"{root}.{fn.attr}() in worker-thread closure "
                        f"{fi.name}() (spawned at {org}) — device "
                        "work enqueued off the dispatch thread can "
                        "deadlock an in-flight pjit (the PR 11 mesh "
                        "hang); materialize to host on the dispatch "
                        "thread and slice numpy, or mark the line "
                        "'# ra12-ok: why' (host-materialized inputs)",
                        roots=(opath,)))
                elif fn.attr == "block_until_ready" and not sub.args:
                    out.append(Finding(
                        mod.path, sub.lineno, "RA12",
                        ".block_until_ready() in worker-thread "
                        f"closure {fi.name}() (spawned at {org}) — a "
                        "worker blocking on device state couples its "
                        "liveness to the dispatch pipeline; gate on "
                        "is_ready() or mark the line "
                        "'# ra12-ok: why'", roots=(opath,)))
            elif isinstance(fn, ast.Name) and fn.id == "device_put":
                out.append(Finding(
                    mod.path, sub.lineno, "RA12",
                    f"device_put() in worker-thread closure "
                    f"{fi.name}() (spawned at {org}) — device "
                    "placement off the dispatch thread is the PR 11 "
                    "deadlock class; stage on the dispatch thread or "
                    "mark the line '# ra12-ok: why'",
                    roots=(opath,)))
    uniq = {}
    for f in out:
        uniq.setdefault(f.key(), f)
    return list(uniq.values())
