"""Declarative closure-gated rule specs + the shared walker (ISSUE 14).

Every closure-gated rule is a :class:`ClosureRule`: scopes (which files
seed roots), root function names, a forbidden-construct kind, and an
allowlist tag.  One engine resolves the roots' CROSS-MODULE transitive
call closure (tools/analyzer/index.py) and walks each reached function
for the rule's forbidden constructs — so a host sync or per-entry
pickle moved into a helper one file away can no longer escape the gate
(the pre-ISSUE-14 checkers only followed same-module calls).

Rules here (the doc-of-record for codes is tools/lint.py's docstring):

  RA02  engine step hot loop: no np.asarray/.item() host syncs
  RA04  bench/soak dispatch loops + sampler/recorder/tuner/mesh tick
        paths: no blocking device->host syncs
  RA08  ingress coalescer + mesh ingress pump: no per-session Python
        loops / dict allocation
  RA09  wire reader sweep path: same, extended to the socket path
  RA10  classic replication hot paths: no per-entry encode/WAL submit
        inside loops, and (the ISSUE 18 codec family) no raw
        ``pickle.dumps`` ANYWHERE in an append/AER/WAL/segment/sweep
        closure — object payloads must ride the codec's tagged
        fallback (ra_tpu.codec.encode_fallback) so every stored or
        shipped byte stays versioned and decodable

Findings are RAW (unsuppressed): tools/analyzer/audit.py applies the
``# raNN-ok`` line allowlists and audits them for staleness.  Tag
FAMILIES: RA02/RA04 are one host-sync family and RA08/RA09 one
per-row-Python family — a line a cross-module closure reaches from two
gates carries ONE documented tag, and the audit accepts either.
"""
from __future__ import annotations

import ast
import os

__all__ = ["Finding", "CLOSURE_RULES", "evaluate_closure_rules",
           "TAG_FAMILIES", "family_codes", "FILE_RULES",
           "evaluate_file_rules"]


class Finding:
    __slots__ = ("path", "line", "code", "msg", "roots")

    def __init__(self, path, line, code, msg, roots=()):
        self.path = path
        self.line = line
        self.code = code
        self.msg = msg
        # provenance: module paths of the rule roots (spawn sites, lock
        # sites, closure seeds) this finding was reached from.  The
        # engine evaluates the WHOLE program so scoped runs match the
        # full run's raw pool (the audit depends on that); the caller
        # then reports a finding only when its path OR one of its roots
        # is a lint target — linting fixture A must not surface sibling
        # B's findings, while a cross-module escape rooted in A still
        # lands wherever the construct lives.
        self.roots = tuple(roots)

    def key(self):
        return (self.path, self.line, self.code, self.msg)

    def render(self):
        return f"{self.path}:{self.line}: {self.code} {self.msg}"


#: allowlist-tag families: a tag from any rule in the family suppresses
#: (and keeps live, for the audit) a finding from any other member —
#: RA02/RA04 police the same host-sync bug class from different roots,
#: RA08/RA09 the same per-row-Python class.
TAG_FAMILIES = (
    ("RA02", "RA04"),
    ("RA08", "RA09"),
    ("RA03",),
    ("RA10",),
    ("RA11",),
    ("RA12",),
    ("RA13",),
    ("RA14",),
    ("RA15",),
    ("RA16",),
)


def family_codes(code):
    for fam in TAG_FAMILIES:
        if code in fam:
            return fam
    return (code,)


# -- scopes ---------------------------------------------------------------

class Scope:
    """Selects root functions inside matching target files."""

    def __init__(self, roots, basenames=None, parent=None, dirname=None):
        self.roots = frozenset(roots)
        self.basenames = frozenset(basenames) if basenames else None
        self.parent = parent      # required parent directory name
        self.dirname = dirname    # any path component (e.g. "wire")

    def matches(self, path):
        base = os.path.basename(path)
        if self.basenames is not None and base not in self.basenames:
            return False
        if self.parent is not None and \
                os.path.basename(os.path.dirname(path)) != self.parent:
            return False
        if self.dirname is not None:
            parts = os.path.normpath(path).split(os.sep)
            if self.dirname not in parts[:-1]:
                return False
        return True


class ClosureRule:
    def __init__(self, code, kind, scopes, msg_ctx):
        self.code = code
        self.kind = kind          # "sync" | "loops" | "per_entry"
        self.scopes = scopes
        self.msg_ctx = msg_ctx    # human name of the gated path


_HOT_STEP_FUNCS = frozenset({"step", "_step", "submit", "uniform_step",
                             "superstep", "_superstep", "submit_block",
                             "uniform_superstep"})
_SAMPLER_HOT_FUNCS = frozenset({"tick", "_start_sample", "_harvest",
                                "note"})

CLOSURE_RULES = [
    ClosureRule("RA02", "sync_ra02",
                [Scope(_HOT_STEP_FUNCS,
                       basenames={"lockstep.py", "durable.py"})],
                "hot-loop"),
    ClosureRule("RA04", "sync",
                [Scope(_SAMPLER_HOT_FUNCS, basenames={"telemetry.py"}),
                 Scope({"record"}, basenames={"blackbox.py"}),
                 Scope({"tick"}, basenames={"autotune.py"}),
                 Scope({"drive_uniform_window"}, basenames={"mesh.py"}),
                 # ISSUE 20: the driver's read observer is a sampler
                 # tick — it must only touch COMPLETED async read-aux
                 # copies, never force a device sync of its own
                 Scope({"_observe_reads"}, basenames={"lockstep.py"})],
                "sampler tick-path"),
    ClosureRule("RA08", "loops",
                [Scope({"offer", "pop_block"},
                       basenames={"coalesce.py"}),
                 Scope({"ingress_submit_wave"}, basenames={"mesh.py"}),
                 # ISSUE 20: the read admission/reply lane — per-WAVE
                 # vectorized, no per-session Python on the hot path
                 Scope({"submit_reads", "_pop_read_block",
                        "_harvest_reads", "_emit_read_replies"},
                       basenames={"__init__.py"}, parent="ingress")],
                "coalescer"),
    ClosureRule("RA09", "loops",
                [Scope({"sweep"}, dirname="wire"),
                 # ISSUE 20: READ_REPLY egress — one frame per
                 # connection per wave, never per read
                 Scope({"_on_reads_served", "collect_read_replies"},
                       basenames={"server.py"}, dirname="wire")],
                "wire sweep"),
    ClosureRule("RA10", "per_entry",
                [Scope({"_send_items", "_wire_form"},
                       basenames={"tcp.py"}),
                 Scope({"write", "append_batch", "_put_batch", "_put",
                        "flush_mem_to_segments"},
                       basenames={"durable.py"}, parent="log"),
                 Scope({"_write_batch"}, basenames={"wal.py"},
                       parent="log"),
                 Scope({"flush"}, basenames={"segment.py"},
                       parent="log"),
                 Scope({"sweep"}, dirname="wire"),
                 Scope({"_leader_aer_reply", "_evaluate_quorum"},
                       basenames={"server.py"}, parent="core")],
                "classic hot path"),
]

#: bench/soak dispatch-loop scope (RA04's loop-shaped half): any loop
#: in these files that dispatches engine work is a measured region
_BENCH_FILES = frozenset({"bench.py", "bench_classic.py", "soak.py"})
_DISPATCH_ATTRS = frozenset({"step", "superstep", "uniform_step",
                             "uniform_superstep", "submit"})
#: ``drain`` is new with ISSUE 14: a driver/sampler drain is a full
#: pipeline barrier, the strongest sync of all — the pre-engine gate
#: missed it (bench.py's probe loop carried a prophylactic tag for it)
_SYNC_ATTRS = frozenset({"block_until_ready", "committed_total", "item",
                         "drain"})
_LOOP_NODES = (ast.For, ast.AsyncFor, ast.While, ast.ListComp,
               ast.SetComp, ast.DictComp, ast.GeneratorExp)
_RA10_ENCODE_NAMES = frozenset({"dumps", "encode_command"})
_RA10_SYNC_NAMES = frozenset({"fsync", "fdatasync"})


# -- forbidden-construct walkers -----------------------------------------

def _walk_sync(fi, code, ctx, out, attrs=_SYNC_ATTRS,
               msg_tail="blocks the dispatch loop the path rides; "
                        "gate on is_ready() or mark the line "
                        "'# ra04-ok: why'"):
    path = fi.module.path
    for sub in ast.walk(fi.node):
        if not isinstance(sub, ast.Call):
            continue
        fn = sub.func
        if not isinstance(fn, ast.Attribute):
            continue
        if fn.attr in attrs and not sub.args:
            out.append(Finding(path, sub.lineno, code,
                               f".{fn.attr}() in {ctx} {fi.name}() "
                               + msg_tail))
        elif fn.attr == "asarray" and \
                isinstance(fn.value, ast.Name) and fn.value.id == "np":
            out.append(Finding(path, sub.lineno, code,
                               f"np.asarray() in {ctx} {fi.name}() "
                               + msg_tail))


def _walk_sync_ra02(fi, code, ctx, out):
    _walk_sync(fi, code, ctx, out, attrs=frozenset({"item"}),
               msg_tail="forces a device->host sync; move it to a "
                        "documented readback point or mark the line "
                        "'# ra02-ok: why'")


def _walk_loops(fi, code, ctx, out):
    path = fi.module.path
    mark = f"# {code.lower()}-ok: why"
    for sub in ast.walk(fi.node):
        if isinstance(sub, _LOOP_NODES):
            out.append(Finding(
                path, sub.lineno, code,
                f"Python loop in {ctx} hot path {fi.name}() — per-row "
                "iteration turns the vectorized path back into "
                "per-command host work; vectorize (argsort/fancy "
                f"indexing) or mark the line '{mark}'"))
        elif isinstance(sub, ast.Dict):
            out.append(Finding(
                path, sub.lineno, code,
                f"dict allocation in {ctx} hot path {fi.name}(); "
                f"preallocate outside the hot path or mark the line "
                f"'{mark}'"))
        elif isinstance(sub, ast.Call) and \
                isinstance(sub.func, ast.Name) and sub.func.id == "dict":
            out.append(Finding(
                path, sub.lineno, code,
                f"dict() allocation in {ctx} hot path {fi.name}(); "
                f"preallocate outside the hot path or mark the line "
                f"'{mark}'"))


def _call_name(call):
    f = call.func
    return f.attr if isinstance(f, ast.Attribute) else \
        f.id if isinstance(f, ast.Name) else None


def _is_encoder(fi):
    for sub in ast.walk(fi.node):
        if isinstance(sub, ast.Call) and \
                _call_name(sub) in _RA10_ENCODE_NAMES:
            return True
    return False


def _is_raw_pickle(call):
    """``pickle.dumps(...)`` or a bare ``dumps``/``_dumps`` alias call
    (the codec's own module-level alias shape) — the construct the
    ISSUE 18 codec family bans from hot closures outside the codec's
    tagged fallback."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr == "dumps" and isinstance(f.value, ast.Name) \
            and f.value.id == "pickle"
    return isinstance(f, ast.Name) and f.id in ("dumps", "_dumps")


def _walk_per_entry(idx, fi, code, ctx, out, encoder_names):
    """RA10: per-entry encode / WAL submit inside a loop, including a
    call to a helper (same-module by name, or cross-module resolved)
    that itself encodes."""
    path = fi.module.path
    seen = set()
    for loop in ast.walk(fi.node):
        if not isinstance(loop, _LOOP_NODES):
            continue
        for sub in ast.walk(loop):
            if not isinstance(sub, ast.Call) or id(sub) in seen:
                continue
            cname = _call_name(sub)
            f = sub.func
            if cname in _RA10_SYNC_NAMES or (
                    cname in ("write", "write_many") and
                    isinstance(f, ast.Attribute) and
                    isinstance(f.value, ast.Attribute) and
                    f.value.attr == "wal"):
                seen.add(id(sub))
                out.append(Finding(
                    path, sub.lineno, code,
                    f"per-entry WAL submit/sync ({cname}) inside a "
                    f"loop in {ctx} {fi.name}() — use the group-commit "
                    "fan-in (write_many) outside the loop or mark the "
                    "line '# ra10-ok: why'"))
            elif cname in _RA10_ENCODE_NAMES or \
                    cname in encoder_names or \
                    any(_is_encoder(c) for c in idx.resolve_call(fi, sub)):
                seen.add(id(sub))
                out.append(Finding(
                    path, sub.lineno, code,
                    f"per-entry encode ({cname}) inside a loop in "
                    f"{ctx} {fi.name}() — batch-encode outside the "
                    "loop (one pickle per frame/run) or mark the line "
                    "'# ra10-ok: why'"))
    # the codec family (ISSUE 18): raw pickle ANYWHERE in the closure,
    # loop or not — a hot-path object-encode that bypasses the codec's
    # tagged fallback ships unversioned bytes to the WAL/wire/segments
    for sub in ast.walk(fi.node):
        if not isinstance(sub, ast.Call) or id(sub) in seen:
            continue
        if _is_raw_pickle(sub):
            seen.add(id(sub))
            out.append(Finding(
                path, sub.lineno, code,
                f"raw pickle.dumps in {ctx} closure {fi.name}() — "
                "object payloads must ride the codec's tagged "
                "fallback (ra_tpu.codec.encode_fallback) so every "
                "stored/shipped byte stays versioned and decodable, "
                "or mark the line '# ra10-ok: why'"))


_WALKERS = {
    "sync": _walk_sync,
    "sync_ra02": _walk_sync_ra02,
    "loops": _walk_loops,
}


def _rule_roots(idx, rule):
    # roots come from EVERY indexed source module, not just the lint
    # targets: a scoped run (--changed, one file) must evaluate the
    # same whole-program pool the full run does, or a tag in a changed
    # helper reads as stale when the root module didn't change (the
    # audit false-failure loop, review finding)
    roots = []
    per_module_names = {}
    for mod in idx.by_path.values():
        if mod.in_tests:
            continue
        names = set()
        for scope in rule.scopes:
            if scope.matches(mod.path):
                names |= scope.roots
        if not names:
            continue
        per_module_names[mod.path] = names
        for n in names:
            roots.extend(mod.func_defs.get(n, []))
    return roots, per_module_names


def evaluate_closure_rules(idx):
    """RAW findings from every declarative closure rule plus the
    bench dispatch-loop half of RA04."""
    out = []
    for rule in CLOSURE_RULES:
        roots, per_module = _rule_roots(idx, rule)
        if not roots:
            continue
        # per-ROOT-MODULE closures so each finding carries exactly the
        # root modules that reach it: stamping the whole rule's root
        # set would make a scoped run report findings only reachable
        # from a DIFFERENT root module (review finding — linting
        # telemetry.py must not surface a mesh-only escape)
        reached_by = {}   # id(fi) -> set of root module paths
        closure = {}
        for mpath, names in per_module.items():
            mod = idx.by_path[mpath]
            mod_roots = []
            for n in names:
                mod_roots.extend(mod.func_defs.get(n, []))
            for fid, fi in idx.closure(mod_roots).items():
                closure[fid] = fi
                reached_by.setdefault(fid, set()).add(mpath)
        if rule.kind == "per_entry":
            # same-module helper-encoder names (legacy superset: bare
            # attr-name matching catches unresolvable self-ish calls)
            encoder_names_by_mod = {}
            for fi in closure.values():
                mpath = fi.module.path
                if mpath not in encoder_names_by_mod:
                    names = set()
                    for defs in fi.module.func_defs.values():
                        for d in defs:
                            if _is_encoder(d):
                                names.add(d.name)
                    encoder_names_by_mod[mpath] = names
        walker = _WALKERS.get(rule.kind)
        for fid, fi in closure.items():
            fi_out = []
            if rule.kind == "per_entry":
                _walk_per_entry(idx, fi, rule.code, rule.msg_ctx,
                                fi_out,
                                encoder_names_by_mod[fi.module.path])
            else:
                walker(fi, rule.code, rule.msg_ctx, fi_out)
            root_paths = tuple(sorted(reached_by[fid]))
            for f in fi_out:
                f.roots = root_paths
            out.extend(fi_out)
    out.extend(_evaluate_bench_loops(idx))
    # dedup: overlapping scopes/roots may reach one function twice
    uniq = {}
    for f in out:
        uniq.setdefault(f.key(), f)
    return list(uniq.values())


def _evaluate_bench_loops(idx):
    """RA04's dispatch-loop half: direct syncs inside a bench/soak loop
    that dispatches engine work, PLUS syncs anywhere in the resolvable
    call closure of helpers the loop body invokes (the cross-module
    escape ISSUE 14 closes)."""
    out = []
    tail = ("inside a bench dispatch loop forces a device->host sync "
            "that serializes the measured pipeline; harvest async "
            "readbacks instead or mark the line '# ra04-ok: why' "
            "(window boundary)")
    for mod in idx.by_path.values():
        if mod.in_tests:
            continue
        if os.path.basename(mod.path) not in _BENCH_FILES:
            continue
        seen = set()
        helper_roots = []
        mod_out = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.While, ast.For, ast.AsyncFor)):
                continue
            body = list(node.body) + list(node.orelse)
            calls = [sub for stmt in body for sub in ast.walk(stmt)
                     if isinstance(sub, ast.Call)
                     and isinstance(sub.func, ast.Attribute)]
            if not any(c.func.attr in _DISPATCH_ATTRS for c in calls):
                continue
            for c in calls:
                if id(c) in seen:
                    continue
                seen.add(id(c))
                attr = c.func.attr
                if attr in ("item", "committed_total") and c.args:
                    continue
                if attr in _SYNC_ATTRS:
                    mod_out.append(Finding(mod.path, c.lineno, "RA04",
                                           f".{attr}() " + tail))
                elif attr == "asarray" and \
                        isinstance(c.func.value, ast.Name) and \
                        c.func.value.id == "np":
                    mod_out.append(Finding(mod.path, c.lineno, "RA04",
                                           "np.asarray() " + tail))
            # cross-module half: helpers the measured loop calls by
            # name — a sync moved into one must not escape the gate
            owner = _enclosing_func(mod, node)
            if owner is None:
                continue
            for stmt in body:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Call) and \
                            isinstance(sub.func, ast.Name):
                        helper_roots.extend(
                            idx.resolve_call(owner, sub))
        if helper_roots:
            for fi in idx.closure(helper_roots).values():
                if fi.node is None:
                    continue
                _walk_sync(fi, "RA04",
                           "a helper reached from a bench dispatch "
                           "loop:", mod_out)
        for f in mod_out:
            f.roots = (mod.path,)
        out.extend(mod_out)
    return out


def _enclosing_func(mod, node):
    """FuncInfo whose body (transitively) contains ``node``."""
    for defs in mod.func_defs.values():
        for fi in defs:
            for sub in ast.walk(fi.node):
                if sub is node:
                    return fi
    return None


# -- declarative per-file rules (RA05/RA06/RA07, migrated from
#    tools/lint.py so ONE engine evaluates every rule — ISSUE 15) --------

class FileRule:
    """A per-file contract: scope (which modules it applies to) + a
    walker ``check(mod, ctx) -> [Finding]``.  Evaluated over EVERY
    indexed module (tests exempt per rule), not just lint targets —
    the same whole-program-pool principle as the closure rules, so a
    scoped run feeds the audit the same raw findings the full run
    does."""

    def __init__(self, code, check, basenames=None, dirnames=None,
                 all_source=False):
        self.code = code
        self.check = check
        self.basenames = frozenset(basenames) if basenames else None
        self.dirnames = frozenset(dirnames) if dirnames else None
        self.all_source = all_source

    def matches(self, mod):
        if mod.in_tests:
            return False
        if self.basenames is not None and \
                os.path.basename(mod.path) in self.basenames:
            return True
        if self.dirnames is not None and os.path.basename(
                os.path.dirname(mod.path)) in self.dirnames:
            return True
        if self.basenames is not None or self.dirnames is not None:
            return False
        return self.all_source


class _FileRuleCtx:
    """Shared resolution context: doc text and event-registry keys are
    resolved NEXT TO the checked file first (self-contained fixtures),
    else from the repo — cached per path."""

    def __init__(self, repo):
        self.repo = repo
        self._doc_cache = {}
        self._keys_cache = {}

    def _read_adjacent(self, path, rel, repo_rel=None):
        """Text of a collaborator file: the copy NEXT TO the checked
        file wins (self-contained fixtures), else the repo's canonical
        location (``repo_rel``, defaulting to ``rel`` off the repo
        root).  ONE resolution helper — the doc, telemetry-overview
        and event-registry lookups all ride it (review finding: three
        hand-rolled copies of the same fallback)."""
        cand = os.path.join(os.path.dirname(path), *rel)
        if not os.path.exists(cand) and self.repo:
            cand = os.path.join(self.repo, *(repo_rel or rel))
        if not os.path.exists(cand):
            return None
        try:
            with open(cand, encoding="utf-8") as f:
                return f.read()
        except OSError:
            return None

    def doc_text(self, path):
        key = os.path.dirname(path)
        if key not in self._doc_cache:
            self._doc_cache[key] = self._read_adjacent(
                path, ("docs", "OBSERVABILITY.md"))
        return self._doc_cache[key]

    def telemetry_text(self, path):
        return self._read_adjacent(path, ("telemetry.py",),
                                   ("ra_tpu", "telemetry.py"))

    def registry_keys(self, path):
        """Keys of blackbox.EVENT_REGISTRY (adjacent-first)."""
        key = os.path.dirname(path)
        if key in self._keys_cache:
            return self._keys_cache[key]
        out = None
        src = self._read_adjacent(path, ("blackbox.py",),
                                  ("ra_tpu", "blackbox.py"))
        if src is not None:
            try:
                tree = ast.parse(src)
            except SyntaxError:
                tree = None
            if tree is not None:
                for node in tree.body:
                    if isinstance(node, ast.Assign) and \
                            len(node.targets) == 1 and \
                            isinstance(node.targets[0], ast.Name) and \
                            node.targets[0].id == "EVENT_REGISTRY" and \
                            isinstance(node.value, ast.Dict):
                        out = {k.value for k in node.value.keys
                               if isinstance(k, ast.Constant)
                               and isinstance(k.value, str)}
        self._keys_cache[key] = out
        return out


def _check_field_registry(mod, ctx):
    """RA05 — the field-group registry contract (metrics.py): a counter
    field FIELD_REGISTRY does not list escapes the registry parity
    test, and one docs/OBSERVABILITY.md does not name is a number
    nobody can interpret — both flagged at the definition site."""
    out = []
    doc_text = ctx.doc_text(mod.path)
    groups = {}
    registry_names = set()
    for node in mod.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        name = node.targets[0].id
        if name.endswith("_FIELDS") and isinstance(node.value, ast.Tuple):
            fields = [e.value for e in node.value.elts
                      if isinstance(e, ast.Constant)
                      and isinstance(e.value, str)]
            groups[name] = (node, fields)
        elif name == "FIELD_REGISTRY" and isinstance(node.value, ast.Dict):
            for v in node.value.values:
                if isinstance(v, ast.Name):
                    registry_names.add(v.id)
    for name, (node, fields) in groups.items():
        if name not in registry_names:
            out.append(Finding(
                mod.path, node.lineno, "RA05",
                f"counter-field tuple {name} is not listed in "
                "FIELD_REGISTRY; the registry parity test cannot "
                "cover it"))
        if doc_text is not None:
            missing = [f for f in fields if f"`{f}`" not in doc_text]
            if missing:
                out.append(Finding(
                    mod.path, node.lineno, "RA05",
                    f"{name} fields undocumented in "
                    f"docs/OBSERVABILITY.md: {missing[:6]}"))
    return out


def _check_event_registry_use(mod, ctx):
    """RA06 (emit half) — every string-constant event type passed to
    the recorder (record(...)/blackbox.record/RECORDER.record) or a
    module-level tracer site (trace.span/trace.instant) must be a
    blackbox.EVENT_REGISTRY key.  Tracer OBJECT spans (t.span) are
    exempt — the registry governs the repo's own instrumentation
    vocabulary."""
    keys = ctx.registry_keys(mod.path)
    if keys is None:
        return []
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        fn = node.func
        via = None
        if isinstance(fn, ast.Name) and fn.id == "record":
            via = "record"
        elif isinstance(fn, ast.Attribute) and fn.attr == "record" and \
                isinstance(fn.value, ast.Name) and \
                fn.value.id in ("blackbox", "RECORDER"):
            via = f"{fn.value.id}.record"
        elif isinstance(fn, ast.Attribute) and \
                fn.attr in ("span", "instant") and \
                isinstance(fn.value, ast.Name) and fn.value.id == "trace":
            via = f"trace.{fn.attr}"
        if via is None:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
                and arg.value not in keys:
            out.append(Finding(
                mod.path, node.lineno, "RA06",
                f"event type {arg.value!r} emitted via {via}() is not "
                "in blackbox.EVENT_REGISTRY; register and document it "
                "(docs/OBSERVABILITY.md) or ra_trace/ra_top cannot "
                "interpret it"))
    return out


def _check_event_registry_doc(mod, ctx):
    """RA06 (doc half, blackbox.py only): every EVENT_REGISTRY key must
    be backticked in docs/OBSERVABILITY.md."""
    out = []
    doc_text = ctx.doc_text(mod.path)
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "EVENT_REGISTRY" and \
                isinstance(node.value, ast.Dict):
            keys = [k.value for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)]
            if doc_text is not None:
                missing = [k for k in keys if f"`{k}`" not in doc_text]
                if missing:
                    out.append(Finding(
                        mod.path, node.lineno, "RA06",
                        "EVENT_REGISTRY keys undocumented in "
                        f"docs/OBSERVABILITY.md: {missing[:6]}"))
    return out


def _tunable_knobs(tree):
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "TUNABLE_KNOBS" and \
                isinstance(node.value, ast.Tuple):
            return [(node, e.value) for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)]
    return []


def _check_autotune_contract(mod, ctx):
    """RA07 — the autotuner contract (autotune.py, ISSUE 9): every
    TUNABLE_KNOBS knob stamped in the engine_pipeline overview
    (telemetry.py next to the file, else the repo's) and documented;
    a knob-mutating function without a registered record(...) event is
    a silent knob turn.  The tick-path no-host-sync half rides the
    RA04 closure gate."""
    out = []
    tree = mod.tree
    path = mod.path
    doc_text = ctx.doc_text(path)
    keys = ctx.registry_keys(path)
    knobs = _tunable_knobs(tree)
    knob_names = {k for _n, k in knobs}
    tel_text = ctx.telemetry_text(path)
    for node, knob in knobs:
        if tel_text is not None and f'"{knob}"' not in tel_text \
                and f"'{knob}'" not in tel_text:
            out.append(Finding(
                path, node.lineno, "RA07",
                f"tunable knob {knob!r} is not stamped in the "
                "engine_pipeline overview (telemetry.py engine "
                "source); a knob the overview does not carry turns "
                "invisibly"))
        if doc_text is not None and f"`{knob}`" not in doc_text:
            out.append(Finding(
                path, node.lineno, "RA07",
                f"tunable knob {knob!r} undocumented in "
                "docs/OBSERVABILITY.md"))
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        mutates = None
        for sub in ast.walk(node):
            targets = []
            if isinstance(sub, ast.Assign):
                targets = sub.targets
            elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                targets = [sub.target]
            for t in targets:
                if isinstance(t, ast.Subscript):
                    base = t.value
                    name = base.attr if isinstance(base, ast.Attribute) \
                        else base.id if isinstance(base, ast.Name) \
                        else None
                    if name == "knobs":
                        mutates = sub
                elif isinstance(t, ast.Attribute) and \
                        t.attr in knob_names:
                    mutates = sub
        if mutates is None:
            continue
        recorded = False
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and sub.args and \
                    isinstance(sub.args[0], ast.Constant) and \
                    isinstance(sub.args[0].value, str):
                fn = sub.func
                name = fn.id if isinstance(fn, ast.Name) else \
                    fn.attr if isinstance(fn, ast.Attribute) else None
                if name == "record" and \
                        (keys is None or sub.args[0].value in keys):
                    recorded = True
        if not recorded:
            out.append(Finding(
                path, mutates.lineno, "RA07",
                f"{node.name}() mutates an autotuner knob without "
                "emitting a registered record(...) event — silent "
                "knob turns are unreconstructable (register the "
                "decision in EVENT_REGISTRY)"))
    return out


#: control-plane calls whose presence makes a While loop a RETRY loop
#: (RA16): commit/query submission, reliable RPC, and pacing sleeps —
#: the verbs a placement/failover escalation loop is built from
_RA16_RETRY_CALLS = frozenset({
    "process_command", "consistent_query", "local_query", "node_call",
    "reliable_node_call", "send_rpc", "sleep", "attempt"})
_RA16_BOUND = ("deadline", "attempt", "tries", "remaining", "budget",
               "retry", "giveup")


def _ra16_local_walk(root):
    """Nodes of ``root`` excluding nested function/lambda bodies (each
    function is judged exactly once, against ITS loops)."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            stack.extend(ast.iter_child_nodes(n))


def _ra16_idents(node):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            yield sub.id
        elif isinstance(sub, ast.Attribute):
            yield sub.attr


def _ra16_has_bound(name_iter):
    return any(b in n.lower() for n in name_iter for b in _RA16_BOUND)


def _check_retry_bounds(mod, ctx):
    """RA16 — no silent infinite retry in the placement/failover
    control plane: a While loop that submits commands / reliable RPCs
    / pacing sleeps must (a) carry deadline-or-attempt bound evidence
    (bound names in the loop test, or a bound-guarded break/raise in
    the body) and (b) live in a function that emits a REGISTERED
    ``record(...)`` event — the give-up a post-mortem can grep for.
    An unbounded escalation loop against a dead peer is exactly how a
    failover wedges forever with nothing in the flight recorder."""
    keys = ctx.registry_keys(mod.path) or set()
    out = []
    funcs = [n for n in ast.walk(mod.tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for fn in funcs:
        gives_up = False
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Call) and sub.args and \
                    isinstance(sub.args[0], ast.Constant) and \
                    sub.args[0].value in keys:
                f = sub.func
                name = f.id if isinstance(f, ast.Name) else \
                    f.attr if isinstance(f, ast.Attribute) else None
                if name == "record":
                    gives_up = True
                    break
        for loop in _ra16_local_walk(fn):
            if not isinstance(loop, ast.While):
                continue
            retry = None
            for sub in ast.walk(loop):
                if isinstance(sub, ast.Call):
                    f = sub.func
                    name = f.id if isinstance(f, ast.Name) else \
                        f.attr if isinstance(f, ast.Attribute) else None
                    if name in _RA16_RETRY_CALLS:
                        retry = name
                        break
            if retry is None:
                continue
            bounded = _ra16_has_bound(_ra16_idents(loop.test))
            if not bounded:
                for sub in ast.walk(loop):
                    if isinstance(sub, ast.If) and \
                            _ra16_has_bound(_ra16_idents(sub.test)) and \
                            any(isinstance(s, (ast.Break, ast.Raise,
                                               ast.Return))
                                for b in sub.body for s in ast.walk(b)):
                        bounded = True
                        break
            if not bounded:
                out.append(Finding(
                    mod.path, loop.lineno, "RA16",
                    f"{fn.name}(): retry loop around {retry}() has no "
                    "deadline/bounded-attempt evidence (no bound name "
                    "in the loop test, no bound-guarded break/raise) — "
                    "an unreachable peer wedges this control-plane "
                    "loop forever"))
            elif not gives_up:
                out.append(Finding(
                    mod.path, loop.lineno, "RA16",
                    f"{fn.name}(): bounded retry loop around "
                    f"{retry}() never emits a registered record(...) "
                    "give-up event — exhaustion is invisible to the "
                    "flight recorder (register one in EVENT_REGISTRY "
                    "and emit it on the give-up path)"))
    return out


def _check_rpc_deadlines(mod, ctx):
    """RA16 (ISSUE 19 extension) — every control-plane RPC call site
    in the placement package must carry an EXPLICIT deadline: a
    ``node_call``/``reliable_node_call`` without a timeout=/deadline
    keyword rides the callee's default budget, which is invisible at
    the call site — the escalation loop that owns the call can no
    longer reason about its own deadline arithmetic (a 60 s hidden
    default inside a 10 s commit window is how a 'bounded' failover
    overshoots its bound)."""
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.id if isinstance(f, ast.Name) else \
            f.attr if isinstance(f, ast.Attribute) else None
        if name not in ("node_call", "reliable_node_call"):
            continue
        if any(kw.arg and ("timeout" in kw.arg.lower()
                           or "deadline" in kw.arg.lower())
               for kw in node.keywords):
            continue
        out.append(Finding(
            mod.path, node.lineno, "RA16",
            f"{name}() call site without an explicit timeout=/deadline "
            "keyword — placement-package RPC calls must state their "
            "own deadline budget (a hidden callee default breaks the "
            "caller's deadline arithmetic)"))
    return out


FILE_RULES = [
    FileRule("RA05", _check_field_registry, basenames={"metrics.py"}),
    FileRule("RA06", _check_event_registry_use, all_source=True),
    FileRule("RA06", _check_event_registry_doc,
             basenames={"blackbox.py"}),
    FileRule("RA07", _check_autotune_contract,
             basenames={"autotune.py"}),
    FileRule("RA16", _check_retry_bounds, dirnames={"placement"}),
    FileRule("RA16", _check_rpc_deadlines, dirnames={"placement"}),
]


def evaluate_file_rules(idx, repo=None):
    """RAW findings from the declarative per-file rules over every
    indexed (non-test) module."""
    ctx = _FileRuleCtx(repo)
    out = []
    for mod in idx.by_path.values():
        for rule in FILE_RULES:
            if rule.matches(mod):
                out.extend(rule.check(mod, ctx))
    uniq = {}
    for f in out:
        uniq.setdefault(f.key(), f)
    return list(uniq.values())
