"""Declarative closure-gated rule specs + the shared walker (ISSUE 14).

Every closure-gated rule is a :class:`ClosureRule`: scopes (which files
seed roots), root function names, a forbidden-construct kind, and an
allowlist tag.  One engine resolves the roots' CROSS-MODULE transitive
call closure (tools/analyzer/index.py) and walks each reached function
for the rule's forbidden constructs — so a host sync or per-entry
pickle moved into a helper one file away can no longer escape the gate
(the pre-ISSUE-14 checkers only followed same-module calls).

Rules here (the doc-of-record for codes is tools/lint.py's docstring):

  RA02  engine step hot loop: no np.asarray/.item() host syncs
  RA04  bench/soak dispatch loops + sampler/recorder/tuner/mesh tick
        paths: no blocking device->host syncs
  RA08  ingress coalescer + mesh ingress pump: no per-session Python
        loops / dict allocation
  RA09  wire reader sweep path: same, extended to the socket path
  RA10  classic replication hot paths: no per-entry encode/WAL submit
        inside loops

Findings are RAW (unsuppressed): tools/analyzer/audit.py applies the
``# raNN-ok`` line allowlists and audits them for staleness.  Tag
FAMILIES: RA02/RA04 are one host-sync family and RA08/RA09 one
per-row-Python family — a line a cross-module closure reaches from two
gates carries ONE documented tag, and the audit accepts either.
"""
from __future__ import annotations

import ast
import os

__all__ = ["Finding", "CLOSURE_RULES", "evaluate_closure_rules",
           "TAG_FAMILIES", "family_codes"]


class Finding:
    __slots__ = ("path", "line", "code", "msg", "roots")

    def __init__(self, path, line, code, msg, roots=()):
        self.path = path
        self.line = line
        self.code = code
        self.msg = msg
        # provenance: module paths of the rule roots (spawn sites, lock
        # sites, closure seeds) this finding was reached from.  The
        # engine evaluates the WHOLE program so scoped runs match the
        # full run's raw pool (the audit depends on that); the caller
        # then reports a finding only when its path OR one of its roots
        # is a lint target — linting fixture A must not surface sibling
        # B's findings, while a cross-module escape rooted in A still
        # lands wherever the construct lives.
        self.roots = tuple(roots)

    def key(self):
        return (self.path, self.line, self.code, self.msg)

    def render(self):
        return f"{self.path}:{self.line}: {self.code} {self.msg}"


#: allowlist-tag families: a tag from any rule in the family suppresses
#: (and keeps live, for the audit) a finding from any other member —
#: RA02/RA04 police the same host-sync bug class from different roots,
#: RA08/RA09 the same per-row-Python class.
TAG_FAMILIES = (
    ("RA02", "RA04"),
    ("RA08", "RA09"),
    ("RA03",),
    ("RA10",),
    ("RA11",),
    ("RA12",),
)


def family_codes(code):
    for fam in TAG_FAMILIES:
        if code in fam:
            return fam
    return (code,)


# -- scopes ---------------------------------------------------------------

class Scope:
    """Selects root functions inside matching target files."""

    def __init__(self, roots, basenames=None, parent=None, dirname=None):
        self.roots = frozenset(roots)
        self.basenames = frozenset(basenames) if basenames else None
        self.parent = parent      # required parent directory name
        self.dirname = dirname    # any path component (e.g. "wire")

    def matches(self, path):
        base = os.path.basename(path)
        if self.basenames is not None and base not in self.basenames:
            return False
        if self.parent is not None and \
                os.path.basename(os.path.dirname(path)) != self.parent:
            return False
        if self.dirname is not None:
            parts = os.path.normpath(path).split(os.sep)
            if self.dirname not in parts[:-1]:
                return False
        return True


class ClosureRule:
    def __init__(self, code, kind, scopes, msg_ctx):
        self.code = code
        self.kind = kind          # "sync" | "loops" | "per_entry"
        self.scopes = scopes
        self.msg_ctx = msg_ctx    # human name of the gated path


_HOT_STEP_FUNCS = frozenset({"step", "_step", "submit", "uniform_step",
                             "superstep", "_superstep", "submit_block",
                             "uniform_superstep"})
_SAMPLER_HOT_FUNCS = frozenset({"tick", "_start_sample", "_harvest",
                                "note"})

CLOSURE_RULES = [
    ClosureRule("RA02", "sync_ra02",
                [Scope(_HOT_STEP_FUNCS,
                       basenames={"lockstep.py", "durable.py"})],
                "hot-loop"),
    ClosureRule("RA04", "sync",
                [Scope(_SAMPLER_HOT_FUNCS, basenames={"telemetry.py"}),
                 Scope({"record"}, basenames={"blackbox.py"}),
                 Scope({"tick"}, basenames={"autotune.py"}),
                 Scope({"drive_uniform_window"}, basenames={"mesh.py"})],
                "sampler tick-path"),
    ClosureRule("RA08", "loops",
                [Scope({"offer", "pop_block"},
                       basenames={"coalesce.py"}),
                 Scope({"ingress_submit_wave"}, basenames={"mesh.py"})],
                "coalescer"),
    ClosureRule("RA09", "loops",
                [Scope({"sweep"}, dirname="wire")],
                "wire sweep"),
    ClosureRule("RA10", "per_entry",
                [Scope({"_send_items"}, basenames={"tcp.py"}),
                 Scope({"write", "append_batch", "_put_batch"},
                       basenames={"durable.py"}, parent="log"),
                 Scope({"_leader_aer_reply", "_evaluate_quorum"},
                       basenames={"server.py"}, parent="core")],
                "classic hot path"),
]

#: bench/soak dispatch-loop scope (RA04's loop-shaped half): any loop
#: in these files that dispatches engine work is a measured region
_BENCH_FILES = frozenset({"bench.py", "bench_classic.py", "soak.py"})
_DISPATCH_ATTRS = frozenset({"step", "superstep", "uniform_step",
                             "uniform_superstep", "submit"})
#: ``drain`` is new with ISSUE 14: a driver/sampler drain is a full
#: pipeline barrier, the strongest sync of all — the pre-engine gate
#: missed it (bench.py's probe loop carried a prophylactic tag for it)
_SYNC_ATTRS = frozenset({"block_until_ready", "committed_total", "item",
                         "drain"})
_LOOP_NODES = (ast.For, ast.AsyncFor, ast.While, ast.ListComp,
               ast.SetComp, ast.DictComp, ast.GeneratorExp)
_RA10_ENCODE_NAMES = frozenset({"dumps", "encode_command"})
_RA10_SYNC_NAMES = frozenset({"fsync", "fdatasync"})


# -- forbidden-construct walkers -----------------------------------------

def _walk_sync(fi, code, ctx, out, attrs=_SYNC_ATTRS,
               msg_tail="blocks the dispatch loop the path rides; "
                        "gate on is_ready() or mark the line "
                        "'# ra04-ok: why'"):
    path = fi.module.path
    for sub in ast.walk(fi.node):
        if not isinstance(sub, ast.Call):
            continue
        fn = sub.func
        if not isinstance(fn, ast.Attribute):
            continue
        if fn.attr in attrs and not sub.args:
            out.append(Finding(path, sub.lineno, code,
                               f".{fn.attr}() in {ctx} {fi.name}() "
                               + msg_tail))
        elif fn.attr == "asarray" and \
                isinstance(fn.value, ast.Name) and fn.value.id == "np":
            out.append(Finding(path, sub.lineno, code,
                               f"np.asarray() in {ctx} {fi.name}() "
                               + msg_tail))


def _walk_sync_ra02(fi, code, ctx, out):
    _walk_sync(fi, code, ctx, out, attrs=frozenset({"item"}),
               msg_tail="forces a device->host sync; move it to a "
                        "documented readback point or mark the line "
                        "'# ra02-ok: why'")


def _walk_loops(fi, code, ctx, out):
    path = fi.module.path
    mark = f"# {code.lower()}-ok: why"
    for sub in ast.walk(fi.node):
        if isinstance(sub, _LOOP_NODES):
            out.append(Finding(
                path, sub.lineno, code,
                f"Python loop in {ctx} hot path {fi.name}() — per-row "
                "iteration turns the vectorized path back into "
                "per-command host work; vectorize (argsort/fancy "
                f"indexing) or mark the line '{mark}'"))
        elif isinstance(sub, ast.Dict):
            out.append(Finding(
                path, sub.lineno, code,
                f"dict allocation in {ctx} hot path {fi.name}(); "
                f"preallocate outside the hot path or mark the line "
                f"'{mark}'"))
        elif isinstance(sub, ast.Call) and \
                isinstance(sub.func, ast.Name) and sub.func.id == "dict":
            out.append(Finding(
                path, sub.lineno, code,
                f"dict() allocation in {ctx} hot path {fi.name}(); "
                f"preallocate outside the hot path or mark the line "
                f"'{mark}'"))


def _call_name(call):
    f = call.func
    return f.attr if isinstance(f, ast.Attribute) else \
        f.id if isinstance(f, ast.Name) else None


def _is_encoder(fi):
    for sub in ast.walk(fi.node):
        if isinstance(sub, ast.Call) and \
                _call_name(sub) in _RA10_ENCODE_NAMES:
            return True
    return False


def _walk_per_entry(idx, fi, code, ctx, out, encoder_names):
    """RA10: per-entry encode / WAL submit inside a loop, including a
    call to a helper (same-module by name, or cross-module resolved)
    that itself encodes."""
    path = fi.module.path
    seen = set()
    for loop in ast.walk(fi.node):
        if not isinstance(loop, _LOOP_NODES):
            continue
        for sub in ast.walk(loop):
            if not isinstance(sub, ast.Call) or id(sub) in seen:
                continue
            cname = _call_name(sub)
            f = sub.func
            if cname in _RA10_SYNC_NAMES or (
                    cname in ("write", "write_many") and
                    isinstance(f, ast.Attribute) and
                    isinstance(f.value, ast.Attribute) and
                    f.value.attr == "wal"):
                seen.add(id(sub))
                out.append(Finding(
                    path, sub.lineno, code,
                    f"per-entry WAL submit/sync ({cname}) inside a "
                    f"loop in {ctx} {fi.name}() — use the group-commit "
                    "fan-in (write_many) outside the loop or mark the "
                    "line '# ra10-ok: why'"))
            elif cname in _RA10_ENCODE_NAMES or \
                    cname in encoder_names or \
                    any(_is_encoder(c) for c in idx.resolve_call(fi, sub)):
                seen.add(id(sub))
                out.append(Finding(
                    path, sub.lineno, code,
                    f"per-entry encode ({cname}) inside a loop in "
                    f"{ctx} {fi.name}() — batch-encode outside the "
                    "loop (one pickle per frame/run) or mark the line "
                    "'# ra10-ok: why'"))


_WALKERS = {
    "sync": _walk_sync,
    "sync_ra02": _walk_sync_ra02,
    "loops": _walk_loops,
}


def _rule_roots(idx, rule):
    # roots come from EVERY indexed source module, not just the lint
    # targets: a scoped run (--changed, one file) must evaluate the
    # same whole-program pool the full run does, or a tag in a changed
    # helper reads as stale when the root module didn't change (the
    # audit false-failure loop, review finding)
    roots = []
    per_module_names = {}
    for mod in idx.by_path.values():
        if mod.in_tests:
            continue
        names = set()
        for scope in rule.scopes:
            if scope.matches(mod.path):
                names |= scope.roots
        if not names:
            continue
        per_module_names[mod.path] = names
        for n in names:
            roots.extend(mod.func_defs.get(n, []))
    return roots, per_module_names


def evaluate_closure_rules(idx):
    """RAW findings from every declarative closure rule plus the
    bench dispatch-loop half of RA04."""
    out = []
    for rule in CLOSURE_RULES:
        roots, per_module = _rule_roots(idx, rule)
        if not roots:
            continue
        # per-ROOT-MODULE closures so each finding carries exactly the
        # root modules that reach it: stamping the whole rule's root
        # set would make a scoped run report findings only reachable
        # from a DIFFERENT root module (review finding — linting
        # telemetry.py must not surface a mesh-only escape)
        reached_by = {}   # id(fi) -> set of root module paths
        closure = {}
        for mpath, names in per_module.items():
            mod = idx.by_path[mpath]
            mod_roots = []
            for n in names:
                mod_roots.extend(mod.func_defs.get(n, []))
            for fid, fi in idx.closure(mod_roots).items():
                closure[fid] = fi
                reached_by.setdefault(fid, set()).add(mpath)
        if rule.kind == "per_entry":
            # same-module helper-encoder names (legacy superset: bare
            # attr-name matching catches unresolvable self-ish calls)
            encoder_names_by_mod = {}
            for fi in closure.values():
                mpath = fi.module.path
                if mpath not in encoder_names_by_mod:
                    names = set()
                    for defs in fi.module.func_defs.values():
                        for d in defs:
                            if _is_encoder(d):
                                names.add(d.name)
                    encoder_names_by_mod[mpath] = names
        walker = _WALKERS.get(rule.kind)
        for fid, fi in closure.items():
            fi_out = []
            if rule.kind == "per_entry":
                _walk_per_entry(idx, fi, rule.code, rule.msg_ctx,
                                fi_out,
                                encoder_names_by_mod[fi.module.path])
            else:
                walker(fi, rule.code, rule.msg_ctx, fi_out)
            root_paths = tuple(sorted(reached_by[fid]))
            for f in fi_out:
                f.roots = root_paths
            out.extend(fi_out)
    out.extend(_evaluate_bench_loops(idx))
    # dedup: overlapping scopes/roots may reach one function twice
    uniq = {}
    for f in out:
        uniq.setdefault(f.key(), f)
    return list(uniq.values())


def _evaluate_bench_loops(idx):
    """RA04's dispatch-loop half: direct syncs inside a bench/soak loop
    that dispatches engine work, PLUS syncs anywhere in the resolvable
    call closure of helpers the loop body invokes (the cross-module
    escape ISSUE 14 closes)."""
    out = []
    tail = ("inside a bench dispatch loop forces a device->host sync "
            "that serializes the measured pipeline; harvest async "
            "readbacks instead or mark the line '# ra04-ok: why' "
            "(window boundary)")
    for mod in idx.by_path.values():
        if mod.in_tests:
            continue
        if os.path.basename(mod.path) not in _BENCH_FILES:
            continue
        seen = set()
        helper_roots = []
        mod_out = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.While, ast.For, ast.AsyncFor)):
                continue
            body = list(node.body) + list(node.orelse)
            calls = [sub for stmt in body for sub in ast.walk(stmt)
                     if isinstance(sub, ast.Call)
                     and isinstance(sub.func, ast.Attribute)]
            if not any(c.func.attr in _DISPATCH_ATTRS for c in calls):
                continue
            for c in calls:
                if id(c) in seen:
                    continue
                seen.add(id(c))
                attr = c.func.attr
                if attr in ("item", "committed_total") and c.args:
                    continue
                if attr in _SYNC_ATTRS:
                    mod_out.append(Finding(mod.path, c.lineno, "RA04",
                                           f".{attr}() " + tail))
                elif attr == "asarray" and \
                        isinstance(c.func.value, ast.Name) and \
                        c.func.value.id == "np":
                    mod_out.append(Finding(mod.path, c.lineno, "RA04",
                                           "np.asarray() " + tail))
            # cross-module half: helpers the measured loop calls by
            # name — a sync moved into one must not escape the gate
            owner = _enclosing_func(mod, node)
            if owner is None:
                continue
            for stmt in body:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Call) and \
                            isinstance(sub.func, ast.Name):
                        helper_roots.extend(
                            idx.resolve_call(owner, sub))
        if helper_roots:
            for fi in idx.closure(helper_roots).values():
                if fi.node is None:
                    continue
                _walk_sync(fi, "RA04",
                           "a helper reached from a bench dispatch "
                           "loop:", mod_out)
        for f in mod_out:
            f.roots = (mod.path,)
        out.extend(mod_out)
    return out


def _enclosing_func(mod, node):
    """FuncInfo whose body (transitively) contains ``node``."""
    for defs in mod.func_defs.values():
        for fi in defs:
            for sub in ast.walk(fi.node):
                if sub is node:
                    return fi
    return None
