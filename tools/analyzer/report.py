"""Output rendering for the analyzer: ``--report`` and ``--json``.

The default lint output contract is unchanged (one
``path:line: CODE msg`` line per finding + the ``lint: N files, M
findings`` summary); these renderers are additive views over the same
finding pool.
"""
from __future__ import annotations

import collections
import json
import os


def render_json(files, active, suppressed, elapsed_s):
    def row(f):
        return {"path": f.path, "line": f.line, "code": f.code,
                "msg": f.msg}
    return json.dumps({
        "files": len(files),
        "findings": [row(f) for f in active],
        "suppressed": [row(f) for f in suppressed],
        "elapsed_s": round(elapsed_s, 4),
    }, indent=2, sort_keys=True)


def render_report(files, active, suppressed, elapsed_s, repo=None):
    """Human-grouped report: per-rule counts, then findings grouped by
    file, then the suppression inventory."""
    lines = []
    lines.append("static analysis report")
    lines.append(f"  files scanned: {len(files)}")
    lines.append(f"  findings: {len(active)} active, "
                 f"{len(suppressed)} suppressed (tagged)")
    lines.append(f"  elapsed: {elapsed_s:.2f}s")
    by_code = collections.Counter(f.code for f in active)
    if by_code:
        lines.append("")
        lines.append("by rule:")
        for code in sorted(by_code):
            lines.append(f"  {code:<6} {by_code[code]}")
    by_file = collections.defaultdict(list)
    for f in active:
        by_file[f.path].append(f)
    if by_file:
        lines.append("")
        lines.append("by file:")
        for path in sorted(by_file):
            rel = os.path.relpath(path, repo) if repo else path
            lines.append(f"  {rel}:")
            for f in sorted(by_file[path], key=lambda x: x.line):
                lines.append(f"    :{f.line} {f.code} {f.msg}")
    if suppressed:
        sup_by_code = collections.Counter(f.code for f in suppressed)
        lines.append("")
        lines.append("suppressed (allowlisted) by rule: " + ", ".join(
            f"{c}={n}" for c, n in sorted(sup_by_code.items())))
    return "\n".join(lines)
