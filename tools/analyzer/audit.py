"""Suppression handling + the allowlist-rot audit (ISSUE 14).

Two halves:

* :func:`apply_suppressions` — partitions RAW findings into
  (active, suppressed) using the per-rule ``# raNN-ok: <why>`` line
  tags (family-aware: an RA02 tag also covers an RA04 finding on the
  same line, see rules.TAG_FAMILIES) and the generic ``noqa`` marker.
  Matching is by line CONTENT (substring), preserving the historical
  lint behaviour.

* :func:`audit_suppressions` — the rot check: every ``raNN-ok`` tag
  that appears as an ACTUAL COMMENT (tokenize, so tags inside string
  literals/docstrings — e.g. fixture sources embedded in tests — are
  ignored) on a line its rule (family) no longer flags is ITSELF an
  error.  Allowlists can't rot: delete the construct and the stale tag
  fails the gate until the comment goes too.  Tests are exempt (their
  tags live inside fixture strings by construction).

Audit findings use the code ``AUDIT`` and name the tag in lowercase
only, so per-rule cleanliness pins (``"RA04" not in output``) never
trip on a stale-tag report.

The tag vocabulary is open-ended by construction (the ``ra\\d\\d-ok``
regex): the ISSUE 15 jit-plane families (``ra13-ok``/``ra14-ok``/
``ra15-ok``) joined with zero audit changes — a new rule family only
has to register in ``rules.TAG_FAMILIES`` to get both suppression and
rot detection.
"""
from __future__ import annotations

import io
import re
import tokenize

from .rules import Finding, family_codes

__all__ = ["apply_suppressions", "audit_suppressions"]

_TAG_RE = re.compile(r"\bra(\d{2})-ok\b")


def _line_cache(paths):
    cache = {}
    for p in paths:
        try:
            with open(p, encoding="utf-8") as f:
                cache[p] = f.read()
        except OSError:
            cache[p] = ""
    return cache


def apply_suppressions(findings, src_by_path=None):
    """(active, suppressed) split of RAW findings."""
    if src_by_path is None:
        src_by_path = _line_cache({f.path for f in findings})
    lines_by_path = {p: s.splitlines() for p, s in src_by_path.items()}
    active, suppressed = [], []
    for f in findings:
        lines = lines_by_path.get(f.path, [])
        line = lines[f.line - 1] if 1 <= f.line <= len(lines) else ""
        tags = {f"ra{m}-ok" for m in _TAG_RE.findall(line)}
        fam_tags = {c.lower() + "-ok" for c in family_codes(f.code)}
        if "noqa" in line or (tags & fam_tags):
            suppressed.append(f)
        else:
            active.append(f)
    return active, suppressed


def _comment_tags(src):
    """{(lineno, tag)} for raNN-ok tags in REAL comment tokens."""
    out = set()
    try:
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type == tokenize.COMMENT:
                for m in _TAG_RE.findall(tok.string):
                    out.add((tok.start[0], f"ra{m}-ok"))
    except (tokenize.TokenError, SyntaxError, IndentationError):
        pass
    return out


def audit_suppressions(target_paths, raw_findings, src_by_path=None,
                       skip_tests=True):
    """AUDIT findings for stale ``raNN-ok`` tags in the target files:
    a tag whose rule family produced NO raw finding on its line no
    longer suppresses anything and must be removed (or the construct
    it documented restored)."""
    if src_by_path is None:
        src_by_path = _line_cache(set(target_paths))
    flagged = {}
    for f in raw_findings:
        flagged.setdefault((f.path, f.line), set()).add(f.code)
    out = []
    for path in target_paths:
        norm = path.replace("\\", "/")
        base = norm.rsplit("/", 1)[-1]
        if skip_tests and ("/tests/" in norm or
                           base.startswith("test_")):
            continue
        src = src_by_path.get(path, "")
        for lineno, tag in sorted(_comment_tags(src)):
            code = "RA" + tag[2:4]
            fam = set(family_codes(code))
            if not (flagged.get((path, lineno), set()) & fam):
                out.append(Finding(
                    path, lineno, "AUDIT",
                    f"stale suppression: '{tag}' tag but its rule no "
                    "longer flags this line — remove the comment (the "
                    "allowlist-rot gate, ISSUE 14) or restore the "
                    "construct it documents"))
    return out
