"""AST index + cross-module call graph (ISSUE 14 tentpole core).

Parses a set of Python files into per-module tables (functions,
classes, imports, lock attributes) and resolves call sites to concrete
function definitions ACROSS module boundaries — the resolution layer
every closure-gated lint rule (RA02/RA04/RA08/RA09/RA10), the RA11
lock-order analyzer and the RA12 thread-role checker walk on.

Resolution strategies, in the order a call site tries them:

* ``name(...)``            — same-module function, or an imported name
                             (``from x import f`` / package re-export
                             chains), or a class constructor
                             (resolves to ``Class.__init__``)
* ``self.m(...)``          — method in the enclosing class's MRO
                             (bases resolve cross-module); falls back
                             to any same-module def named ``m`` (the
                             pre-ISSUE-14 same-module behaviour, kept
                             so the old gates never lose coverage)
* ``mod.f(...)``           — function in an imported module;
                             ``Class.m(...)`` for imported classes
* ``var.m(...)``           — local variable typed by a parameter
                             annotation (``def f(d: Driver)``), an
                             assignment from a resolvable constructor
                             (``d = Driver(...)``), or a called
                             function's return annotation
* ``self.attr.m(...)``     — instance attribute typed by
                             ``self.attr = Class(...)``, an annotated
                             ``__init__`` parameter assigned to it, an
                             ``attr: Class`` AnnAssign, or an explicit
                             ``# ra-type: Class`` line comment (the
                             small annotation ISSUE 14 adds for
                             dynamically passed collaborators)

Anything deeper (callbacks stored in dicts, ``x[i].m()``, duck-typed
parameters without annotations) is deliberately unresolved: the
analyzer only follows edges it can prove, and the docs record the
limitation (docs/INTERNALS.md §15).

Stdlib-only (``ast``): the image ships no ruff/mypy and installing
tools is off the table.
"""
from __future__ import annotations

import ast
import os

#: constructors whose ``self.x = threading.X()`` assignment marks
#: ``x`` as a lock attribute (RA11 harvests acquisitions of these)
LOCK_CTORS = frozenset({"Lock", "RLock", "Condition", "Semaphore",
                        "BoundedSemaphore"})
#: lock ctors a thread may re-acquire while already holding without a
#: GUARANTEED deadlock — RLock is reentrant, the default Condition
#: wraps an RLock, and semaphores admit multiple holders.  A plain
#: Lock is absent: re-entering one blocks its own thread forever, and
#: RA11 reports that self-edge as a one-lock cycle (locks.edges()).
REENTRANT_CTORS = frozenset({"RLock", "Condition", "Semaphore",
                             "BoundedSemaphore"})


class FuncInfo:
    __slots__ = ("name", "qualname", "module", "node", "cls")

    def __init__(self, name, qualname, module, node, cls=None):
        self.name = name
        self.qualname = qualname
        self.module = module
        self.node = node
        self.cls = cls

    def __repr__(self):  # pragma: no cover - debug aid
        return f"<Func {self.module.name}:{self.qualname}>"


class ClassInfo:
    __slots__ = ("name", "module", "node", "methods", "base_exprs",
                 "attr_refs", "lock_attrs", "_mro")

    def __init__(self, name, module, node):
        self.name = name
        self.module = module
        self.node = node
        self.methods = {}      # name -> FuncInfo (direct only)
        self.base_exprs = []   # ast exprs of bases
        self.attr_refs = {}    # attr -> type ref (ast node or str)
        self.lock_attrs = {}   # attr -> ctor name ("Lock"/"RLock"/...)
        self._mro = None

    def __repr__(self):  # pragma: no cover - debug aid
        return f"<Class {self.module.name}:{self.name}>"


class ModuleInfo:
    __slots__ = ("path", "name", "stem", "tree", "lines", "functions",
                 "classes", "import_mod", "import_name", "func_defs",
                 "module_locks", "is_target", "in_tests", "in_package")

    def __init__(self, path, name, stem, tree, lines):
        self.path = path
        self.name = name            # dotted name when under a package
        self.stem = stem
        self.tree = tree
        self.lines = lines
        self.functions = {}         # module-level funcs: name -> FuncInfo
        self.classes = {}           # name -> ClassInfo
        self.import_mod = {}        # alias -> (dotted, level)
        self.import_name = {}       # alias -> (dotted, orig, level)
        self.func_defs = {}         # bare name -> [FuncInfo] (ALL defs)
        self.module_locks = {}      # name -> ctor name
        self.is_target = False
        parts = set(os.path.normpath(path).split(os.sep))
        self.in_tests = "tests" in parts or \
            os.path.basename(path).startswith("test_")
        self.in_package = os.path.exists(
            os.path.join(os.path.dirname(path), "__init__.py"))

    def line(self, lineno):
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


def _module_name(path):
    """Dotted module name + search root: walk up while the directory is
    a package (__init__.py)."""
    stem = os.path.splitext(os.path.basename(path))[0]
    d = os.path.dirname(os.path.abspath(path))
    parts = [] if stem == "__init__" else [stem]
    while os.path.exists(os.path.join(d, "__init__.py")):
        parts.insert(0, os.path.basename(d))
        d = os.path.dirname(d)
    return ".".join(parts) or stem, d


def _annotation_expr(node):
    """Unwrap Optional[X]/X | None style annotations to the inner
    type expression."""
    if isinstance(node, ast.Subscript):
        base = node.value
        name = base.id if isinstance(base, ast.Name) else \
            base.attr if isinstance(base, ast.Attribute) else None
        if name == "Optional":
            return node.slice
        return None
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr):
        # X | None
        for side in (node.left, node.right):
            if not (isinstance(side, ast.Constant) and side.value is None):
                return side
        return None
    return node


def _lock_ctor_name(call):
    """'Lock'/'RLock'/... when ``call`` constructs a threading lock."""
    if not isinstance(call, ast.Call):
        return None
    fn = call.func
    if isinstance(fn, ast.Attribute) and \
            isinstance(fn.value, ast.Name) and \
            fn.value.id == "threading" and fn.attr in LOCK_CTORS:
        return fn.attr
    if isinstance(fn, ast.Name) and fn.id in LOCK_CTORS:
        return fn.id
    return None


def root_name(expr):
    """Leftmost Name of a dotted attribute chain, or None — shared by
    the thread-role (RA12) and jit-plane (RA13-15) checkers (one
    definition; the two copies had already started life identical,
    review finding)."""
    while isinstance(expr, ast.Attribute):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


def iter_scope(node):
    """``ast.walk`` that does not descend into NESTED function/lambda
    definitions: the enclosing function's own executable scope.  A
    ``with self._lock:`` body that merely DEFINES a callback does not
    run it while the lock is held — lock/edge harvesting must not
    attribute the callback's acquisitions to the outer scope."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            stack.append(child)


def _param_annotations(fn_node):
    args = fn_node.args
    out = {}
    for a in list(args.posonlyargs) + list(args.args) + \
            list(args.kwonlyargs):
        if a.annotation is not None:
            out[a.arg] = a.annotation
    return out


class PackageIndex:
    """Index over a set of files; the resolution + closure engine."""

    def __init__(self):
        self.by_path = {}        # abspath -> ModuleInfo
        self.search_dirs = []    # roots for absolute-import resolution
        self._callee_memo = {}   # id(FuncInfo) -> [FuncInfo]
        self._scoped_callee_memo = {}
        self._local_type_memo = {}

    # -- construction ------------------------------------------------------

    def add_file(self, path, is_target=False):
        path = os.path.abspath(path)
        mod = self.by_path.get(path)
        if mod is None:
            try:
                with open(path, encoding="utf-8") as f:
                    src = f.read()
                tree = ast.parse(src, path)
            except (OSError, SyntaxError):
                return None
            name, root = _module_name(path)
            stem = os.path.splitext(os.path.basename(path))[0]
            mod = ModuleInfo(path, name, stem, tree, src.splitlines())
            self.by_path[path] = mod
            if root not in self.search_dirs:
                self.search_dirs.append(root)
            self._build_module(mod)
        if is_target:
            mod.is_target = True
        return mod

    def _build_module(self, mod):
        # imports harvested from the WHOLE tree: this codebase defers
        # imports into functions to break cycles, and resolution must
        # see those edges too (shadowing by scope is ignored — a wrong
        # edge only ever ADDS a function to a closure)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    bound = alias.asname or alias.name.split(".")[0]
                    dotted = alias.name if alias.asname else \
                        alias.name.split(".")[0]
                    mod.import_mod.setdefault(bound, (dotted, 0))
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    bound = alias.asname or alias.name
                    mod.import_name.setdefault(
                        bound, (base, alias.name, node.level))
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi = FuncInfo(node.name, node.name, mod, node)
                mod.functions.setdefault(node.name, fi)
            elif isinstance(node, ast.ClassDef):
                ci = ClassInfo(node.name, mod, node)
                ci.base_exprs = list(node.bases)
                mod.classes[node.name] = ci
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        fi = FuncInfo(sub.name,
                                      f"{node.name}.{sub.name}",
                                      mod, sub, ci)
                        ci.methods.setdefault(sub.name, fi)
                self._scan_class_attrs(mod, ci)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                ctor = _lock_ctor_name(node.value)
                if ctor:
                    mod.module_locks[node.targets[0].id] = ctor
        # bare-name fallback table: EVERY def in the file (incl. nested),
        # preserving the pre-ISSUE-14 same-module resolution superset
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cls = None
                qual = node.name
                for ci in mod.classes.values():
                    if node in ci.node.body:
                        cls = ci
                        qual = f"{ci.name}.{node.name}"
                        break
                known = (cls.methods.get(node.name) if cls
                         else mod.functions.get(node.name))
                fi = known if known is not None and known.node is node \
                    else FuncInfo(node.name, qual, mod, node, cls)
                mod.func_defs.setdefault(node.name, []).append(fi)

    def _scan_class_attrs(self, mod, ci):
        """Type + lock harvesting for ``self.attr`` assignments across
        every method of the class."""
        for m in ci.methods.values():
            anns = _param_annotations(m.node)
            for sub in ast.walk(m.node):
                target = None
                value = None
                ann = None
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    target, value = sub.targets[0], sub.value
                elif isinstance(sub, ast.AnnAssign):
                    target, value, ann = sub.target, sub.value, \
                        sub.annotation
                if not (isinstance(target, ast.Attribute) and
                        isinstance(target.value, ast.Name) and
                        target.value.id == "self"):
                    continue
                attr = target.attr
                ctor = _lock_ctor_name(value)
                if ctor:
                    ci.lock_attrs.setdefault(attr, ctor)
                    continue
                # explicit hint wins: `self.x = y  # ra-type: Class`
                line = mod.line(getattr(sub, "lineno", 0))
                if "# ra-type:" in line:
                    hint = line.split("# ra-type:", 1)[1].strip()
                    hint = hint.split()[0] if hint else ""
                    if hint:
                        ci.attr_refs[attr] = hint
                        continue
                if ann is not None:
                    ci.attr_refs.setdefault(attr, _annotation_expr(ann))
                elif isinstance(value, ast.Call):
                    ci.attr_refs.setdefault(attr, value)
                elif isinstance(value, ast.Name) and value.id in anns:
                    ci.attr_refs.setdefault(
                        attr, _annotation_expr(anns[value.id]))

    # -- module / name resolution -----------------------------------------

    def _module_by_parts(self, base_dir, parts):
        cand = os.path.join(base_dir, *parts) + ".py"
        m = self.by_path.get(os.path.abspath(cand))
        if m is not None:
            return m
        cand = os.path.join(base_dir, *parts, "__init__.py")
        return self.by_path.get(os.path.abspath(cand))

    def resolve_module(self, from_mod, dotted, level=0):
        parts = [p for p in dotted.split(".") if p] if dotted else []
        if level:
            d = os.path.dirname(from_mod.path)
            for _ in range(level - 1):
                d = os.path.dirname(d)
            return self._module_by_parts(d, parts) if parts else \
                self.by_path.get(os.path.abspath(
                    os.path.join(d, "__init__.py")))
        if not parts:
            return None
        # sibling-first (the fixture idiom: `from blackbox import x`
        # next to the checked file), then each package search root
        sib = self._module_by_parts(os.path.dirname(from_mod.path), parts)
        if sib is not None:
            return sib
        for root in self.search_dirs:
            m = self._module_by_parts(root, parts)
            if m is not None:
                return m
        return None

    def resolve_name(self, mod, name, _depth=0):
        """('func'|'class'|'module', info) for a bare name in ``mod``,
        following import chains up to a small depth."""
        if _depth > 6:
            return None
        if name in mod.functions:
            return ("func", mod.functions[name])
        if name in mod.classes:
            return ("class", mod.classes[name])
        if name in mod.import_name:
            base, orig, level = mod.import_name[name]
            target = self.resolve_module(mod, base, level)
            if target is not None:
                got = self.resolve_name(target, orig, _depth + 1)
                if got is not None:
                    return got
                # `from pkg.x import y` where y is itself a module
                sub = self.resolve_module(
                    target, orig, 1) if target.stem == "__init__" or \
                    os.path.basename(target.path) == "__init__.py" \
                    else None
                if sub is not None:
                    return ("module", sub)
            # unresolved import target: maybe `from a.b import c` with
            # a.b.c being a module file
            dotted = f"{base}.{orig}" if base else orig
            sub = self.resolve_module(mod, dotted, level)
            if sub is not None:
                return ("module", sub)
            return None
        if name in mod.import_mod:
            dotted, level = mod.import_mod[name]
            target = self.resolve_module(mod, dotted, level)
            if target is not None:
                return ("module", target)
        return None

    def resolve_type(self, mod, ref, _depth=0):
        """ClassInfo for a type reference: ast Name/Attribute/Constant
        string annotation, or a plain string hint."""
        if ref is None or _depth > 6:
            return None
        if isinstance(ref, str):
            parts = ref.split(".")
            if len(parts) == 1:
                got = self.resolve_name(mod, parts[0])
                return got[1] if got and got[0] == "class" else None
            got = self.resolve_name(mod, parts[0])
            if got and got[0] == "module":
                return self.resolve_type(got[1], ".".join(parts[1:]),
                                         _depth + 1)
            # fully-qualified hint (`# ra-type: pkg.mod.Class`): try
            # every module/class split against the search roots, so a
            # hint works even where the module is not imported
            for i in range(len(parts) - 1, 0, -1):
                target = self.resolve_module(mod, ".".join(parts[:i]))
                if target is not None:
                    if i == len(parts) - 1:
                        ci = target.classes.get(parts[i])
                        if ci is not None:
                            return ci
                    else:
                        got2 = self.resolve_type(
                            target, ".".join(parts[i:]), _depth + 1)
                        if got2 is not None:
                            return got2
            return None
        if isinstance(ref, ast.Constant) and isinstance(ref.value, str):
            return self.resolve_type(mod, ref.value, _depth + 1)
        if isinstance(ref, ast.Call):
            # `self.x = ClassName(...)` — the constructor IS the type
            return self.resolve_type(mod, ref.func, _depth + 1)
        if isinstance(ref, ast.Name):
            got = self.resolve_name(mod, ref.id)
            return got[1] if got and got[0] == "class" else None
        if isinstance(ref, ast.Attribute) and \
                isinstance(ref.value, ast.Name):
            got = self.resolve_name(mod, ref.value.id)
            if got and got[0] == "module":
                inner = got[1].classes.get(ref.attr)
                if inner is not None:
                    return inner
                got2 = self.resolve_name(got[1], ref.attr)
                return got2[1] if got2 and got2[0] == "class" else None
        sub = _annotation_expr(ref) if isinstance(ref, ast.AST) else None
        if sub is not None and sub is not ref:
            return self.resolve_type(mod, sub, _depth + 1)
        return None

    def mro(self, ci):
        if ci._mro is not None:
            return ci._mro
        ci._mro = [ci]  # cycle guard: partial result visible to reentry
        out = [ci]
        for b in ci.base_exprs:
            base = self.resolve_type(ci.module, b)
            if base is not None and base is not ci:
                for anc in self.mro(base):
                    if anc not in out:
                        out.append(anc)
        ci._mro = out
        return out

    def find_method(self, ci, name):
        for anc in self.mro(ci):
            m = anc.methods.get(name)
            if m is not None:
                return m
        return None

    def lock_attr_ctor(self, ci, attr):
        """Lock ctor name for ``attr`` through the class MRO, with the
        DEFINING class — locks are named by where they are created."""
        for anc in self.mro(ci):
            if attr in anc.lock_attrs:
                return anc.lock_attrs[attr], anc
        return None, None

    def attr_type(self, ci, attr):
        for anc in self.mro(ci):
            ref = anc.attr_refs.get(attr)
            if ref is not None:
                return self.resolve_type(anc.module, ref)
        return None

    # -- local variable typing --------------------------------------------

    def local_types(self, fi):
        memo = self._local_type_memo.get(id(fi))
        if memo is not None:
            return memo
        types = {}
        # install the (still partial) dict up front: _attr_chain_type
        # resolves Name bases through it while the scan below runs
        self._local_type_memo[id(fi)] = types
        anns = _param_annotations(fi.node)
        for name, ann in anns.items():
            t = self.resolve_type(fi.module, _annotation_expr(ann))
            if t is not None:
                types[name] = t
        for sub in ast.walk(fi.node):
            if not (isinstance(sub, ast.Assign) and
                    len(sub.targets) == 1 and
                    isinstance(sub.targets[0], ast.Name)):
                continue
            name = sub.targets[0].id
            v = sub.value
            if isinstance(v, ast.Call):
                callee = self._callable_target(fi, v)
                if isinstance(callee, ClassInfo):
                    types[name] = callee
                elif isinstance(callee, FuncInfo) and \
                        callee.node.returns is not None:
                    t = self.resolve_type(
                        callee.module,
                        _annotation_expr(callee.node.returns))
                    if t is not None:
                        types[name] = t
            elif isinstance(v, ast.Attribute):
                t = self._attr_chain_type(fi, v)
                if t is not None:
                    types[name] = t
        return types

    def _attr_chain_type(self, fi, node):
        """Type of `self.a`, `self.a.b`, `var.a` attribute chains."""
        if isinstance(node, ast.Name):
            if node.id == "self":
                return fi.cls
            return self._local_type_memo.get(id(fi), {}).get(node.id)
        if isinstance(node, ast.Attribute):
            base = self._attr_chain_type(fi, node.value)
            if isinstance(base, ClassInfo):
                return self.attr_type(base, node.attr)
        return None

    def _callable_target(self, fi, call):
        """ClassInfo (constructor) / FuncInfo the call invokes, pre-
        method-resolution — used for local type inference."""
        fn = call.func
        if isinstance(fn, ast.Name):
            got = self.resolve_name(fi.module, fn.id)
            if got is not None:
                return got[1]
        elif isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name):
            got = self.resolve_name(fi.module, fn.value.id)
            if got and got[0] == "module":
                got2 = self.resolve_name(got[1], fn.attr)
                if got2 is not None:
                    return got2[1]
        return None

    # -- call resolution ---------------------------------------------------

    def resolve_call(self, fi, call):
        """FuncInfos a call site may invoke (best-effort, proof-only)."""
        fn = call.func
        out = []
        if isinstance(fn, ast.Name):
            got = self.resolve_name(fi.module, fn.id)
            if got is not None:
                kind, info = got
                if kind == "func":
                    out.append(info)
                elif kind == "class":
                    init = self.find_method(info, "__init__")
                    if init is not None:
                        out.append(init)
            elif fn.id in fi.module.func_defs and \
                    fn.id not in fi.module.functions:
                # nested def referenced by bare name (legacy superset)
                out.extend(fi.module.func_defs[fn.id])
        elif isinstance(fn, ast.Attribute):
            attr = fn.attr
            base = fn.value
            if isinstance(base, ast.Name) and base.id == "self" \
                    and fi.cls is not None:
                m = self.find_method(fi.cls, attr)
                if m is not None:
                    out.append(m)
                else:
                    # pre-ISSUE-14 fallback: any same-module def by name
                    out.extend(fi.module.func_defs.get(attr, []))
            elif isinstance(base, ast.Name) and base.id == "self":
                out.extend(fi.module.func_defs.get(attr, []))
            elif isinstance(base, ast.Name):
                got = self.resolve_name(fi.module, base.id)
                if got is not None:
                    kind, info = got
                    if kind == "module":
                        got2 = self.resolve_name(info, attr)
                        if got2 and got2[0] == "func":
                            out.append(got2[1])
                        elif got2 and got2[0] == "class":
                            init = self.find_method(got2[1], "__init__")
                            if init is not None:
                                out.append(init)
                    elif kind == "class":
                        m = self.find_method(info, attr)
                        if m is not None:
                            out.append(m)
                else:
                    t = self.local_types(fi).get(base.id)
                    if isinstance(t, ClassInfo):
                        m = self.find_method(t, attr)
                        if m is not None:
                            out.append(m)
            elif isinstance(base, ast.Attribute):
                t = self._attr_chain_type(fi, base)
                if isinstance(t, ClassInfo):
                    m = self.find_method(t, attr)
                    if m is not None:
                        out.append(m)
        return out

    def callees(self, fi):
        memo = self._callee_memo.get(id(fi))
        if memo is not None:
            return memo
        self.local_types(fi)  # prime the memo for _attr_chain_type
        out = []
        seen = set()
        for sub in ast.walk(fi.node):
            if isinstance(sub, ast.Call):
                for callee in self.resolve_call(fi, sub):
                    if id(callee) not in seen and callee.node is not \
                            fi.node:
                        seen.add(id(callee))
                        out.append(callee)
        self._callee_memo[id(fi)] = out
        return out

    def callees_scoped(self, fi):
        """Like :meth:`callees` but only for call sites in ``fi``'s own
        executable scope (nested defs excluded) — the lock analyzer's
        edge semantics: a callback defined under a lock is not CALLED
        under it."""
        memo = self._scoped_callee_memo.get(id(fi))
        if memo is not None:
            return memo
        self.local_types(fi)
        out = []
        seen = set()
        for sub in iter_scope(fi.node):
            if isinstance(sub, ast.Call):
                for callee in self.resolve_call(fi, sub):
                    if id(callee) not in seen and \
                            callee.node is not fi.node:
                        seen.add(id(callee))
                        out.append(callee)
        self._scoped_callee_memo[id(fi)] = out
        return out

    def closure(self, roots):
        """Transitive cross-module call closure from the given
        FuncInfos; returns {id: FuncInfo} in BFS order."""
        out = {}
        queue = list(roots)
        while queue:
            fi = queue.pop(0)
            if id(fi) in out:
                continue
            out[id(fi)] = fi
            queue.extend(self.callees(fi))
        return out


def build_index(targets, repo=None, default_sources=None):
    """Index the target files plus everything they may resolve into:
    same-directory siblings (the fixture idiom), the enclosing package
    tree, and — for files inside the repo — the repo's default source
    roots, so single-file invocations resolve cross-module edges the
    same way the full run does."""
    idx = PackageIndex()
    extra = set()
    repo_abs = os.path.abspath(repo) if repo else None
    listed_dirs = set()
    walked_pkgs = set()
    for t in targets:
        t = os.path.abspath(t)
        d = os.path.dirname(t)
        if d not in listed_dirs:
            listed_dirs.add(d)
            try:
                for n in os.listdir(d):
                    if n.endswith(".py"):
                        extra.add(os.path.join(d, n))
            except OSError:
                pass
        # enclosing package tree — every ra_tpu/* target resolves the
        # same root, so walk each root ONCE (the default 131-file run
        # used to do 70 full-tree os.walk passes, ~1.3s of the gate's
        # ~4s; review finding)
        pkg = d
        while os.path.exists(os.path.join(pkg, "__init__.py")):
            pkg = os.path.dirname(pkg)
        if pkg != d and pkg not in walked_pkgs:
            walked_pkgs.add(pkg)
            for root, dirs, names in os.walk(pkg):
                dirs[:] = [x for x in dirs
                           if x not in ("__pycache__", ".git",
                                        ".pytest_cache")]
                extra.update(os.path.join(root, n) for n in names
                             if n.endswith(".py"))
        if repo_abs and t.startswith(repo_abs + os.sep) and \
                default_sources:
            extra.update(default_sources)
    for t in targets:
        idx.add_file(t, is_target=True)
    for e in extra:
        idx.add_file(e, is_target=False)
    return idx
