"""RA11 — whole-program lock-order analyzer (ISSUE 14 tentpole part 2).

Harvests lock acquisitions (``with self._lock:``-style contexts over
attributes assigned ``threading.Lock()/RLock()/Condition()``, plus an
explicit ``# ra11-lock: Class.attr [Ctor]`` line annotation for
dynamically passed locks — the optional second token pins the
constructor, else the named class's indexed lock attr decides, else
the ctor stays unknown), builds the global acquisition-order graph — an edge
``A -> B`` means "B is acquired (directly or through any resolvable
call chain, cross-module) while A is held" — and reports every cycle:
two code paths that take the same pair of locks in opposite orders are
the ABBA deadlock class the PR 13 review caught by hand in
``log/durable.py`` (``_lock`` vs ``_io_lock``).

Lock identity is ``DefiningClass.attr`` (or ``module.name`` for
module-level locks): every instance of a class shares the node, which
is exactly the granularity a lock HIERARCHY is documented at
(docs/INTERNALS.md §15).  Reentrant re-acquisition of the SAME lock
(``RLock``, or a ``Condition`` used by its owner) is not an edge —
but re-entering a plain ``Lock`` while holding it IS reported, as a
one-lock cycle: a non-reentrant self-acquisition blocks its own
thread forever (index.REENTRANT_CTORS draws the line).

Known limitations (documented, deliberate): callbacks stored in
containers (``self._subs[uid](...)``, ``w.notify(...)``) and locks
reached through unannotated parameters are invisible — the analyzer
follows only provable edges.  ``# ra11-ok: <why>`` on an edge's
acquisition line allowlists a reviewed false positive.
"""
from __future__ import annotations

import ast

from .index import REENTRANT_CTORS, iter_scope
from .rules import Finding

__all__ = ["evaluate_lock_order"]


class _LockNode:
    __slots__ = ("key", "ctor")

    def __init__(self, key, ctor):
        self.key = key
        self.ctor = ctor


def _with_lock_items(idx, fi, node):
    """(lock_key, ctor) for each known-lock context manager of a With
    statement; unknown context managers resolve to nothing."""
    out = []
    mod = fi.module
    line = mod.line(node.lineno)
    hint = None
    if "# ra11-lock:" in line:
        hint = line.split("# ra11-lock:", 1)[1].strip() or None
    for item in node.items:
        expr = item.context_expr
        got = _resolve_lock_expr(idx, fi, expr)
        if got is None and hint:
            got = _hint_lock(idx, hint)
            hint = None  # one annotation names one lock
        if got is not None:
            out.append(got)
    return out


def _hint_lock(idx, hint):
    """Lock node for a ``# ra11-lock: Class.attr [Ctor]`` annotation.
    The optional second token pins the constructor; otherwise the
    named class's indexed lock attr decides; otherwise the ctor is
    None — UNKNOWN, which still orders ABBA edges but is never claimed
    to be a guaranteed self-deadlock (the annotation is the escape
    hatch for locks the resolver cannot type, so a forced 'Lock' here
    false-positived on annotated RLocks/Conditions — review finding)."""
    toks = hint.split()
    key = toks[0]
    ctor = toks[1] if len(toks) > 1 else None
    if ctor is None and "." in key:
        cls_name, attr = key.rsplit(".", 1)
        for mod in idx.by_path.values():
            ci = mod.classes.get(cls_name)
            if ci is not None:
                got, _defining = idx.lock_attr_ctor(ci, attr)
                if got is not None:
                    ctor = got
                    break
    return (key, ctor)


def _resolve_lock_expr(idx, fi, expr):
    mod = fi.module
    if isinstance(expr, ast.Name):
        ctor = mod.module_locks.get(expr.id)
        if ctor:
            return (f"{mod.stem}.{expr.id}", ctor)
        # local variable aliased from an attribute chain:
        # ``cond = self.bridge._cond; with cond:``
        tgt = _local_lock_binding(idx, fi, expr.id)
        if tgt is not None:
            return tgt
        return None
    if isinstance(expr, ast.Attribute):
        return _attr_lock(idx, fi, expr)
    return None


def _attr_lock(idx, fi, expr):
    """Lock node for ``self.X`` / ``self.obj.X`` / ``var.X``."""
    base = expr.value
    attr = expr.attr
    owner = None
    if isinstance(base, ast.Name):
        if base.id == "self":
            owner = fi.cls
        else:
            owner = idx.local_types(fi).get(base.id)
    elif isinstance(base, ast.Attribute):
        owner = idx._attr_chain_type(fi, base)
    if owner is None:
        return None
    ctor, defining = idx.lock_attr_ctor(owner, attr)
    if ctor is None:
        return None
    return (f"{defining.name}.{attr}", ctor)


def _local_lock_binding(idx, fi, name):
    """Resolve ``name`` when a function body binds it to a lock
    attribute chain (one level of aliasing, assignment-order blind —
    good enough for the ``cond = self.bridge._cond`` idiom)."""
    for sub in ast.walk(fi.node):
        if isinstance(sub, ast.Assign) and len(sub.targets) == 1 and \
                isinstance(sub.targets[0], ast.Name) and \
                sub.targets[0].id == name and \
                isinstance(sub.value, ast.Attribute):
            got = _attr_lock(idx, fi, sub.value)
            if got is not None:
                return got
    return None


class _LockWorld:
    """Per-index lock database: per-function acquired-lock sets
    (transitive) and the global acquisition-order edge list."""

    def __init__(self, idx):
        self.idx = idx
        self._acquired = {}
        self._built = set()
        self.ctors = {}   # lock key -> ctor name (first sighting wins)

    def _direct_locks(self, fi):
        out = set()
        # same-scope only: a nested def's acquisitions belong to the
        # nested function (it has its own FuncInfo), not to the scope
        # that merely defines it
        for sub in iter_scope(fi.node):
            if isinstance(sub, (ast.With, ast.AsyncWith)):
                for key, ctor in _with_lock_items(self.idx, fi, sub):
                    out.add(key)
                    # None = unknown (unresolved annotation): never
                    # recorded, so a later PROVEN sighting of the same
                    # key still lands regardless of traversal order
                    if ctor is not None:
                        self.ctors.setdefault(key, ctor)
        return out

    def _build(self, seeds):
        """Order-independent transitive acquired-lock sets for every
        function reachable from ``seeds``: collapse the call graph's
        SCCs (Tarjan emits them callees-first) and propagate each
        SCC's union downstream->up.  A plain DFS memo truncates at
        cycles, so mutually recursive lock-takers would memoize
        PARTIAL sets depending on traversal order — a missed-ABBA
        false negative (caught in review)."""
        funcs = {}
        stack = list(seeds)
        while stack:
            fi = stack.pop()
            if id(fi) in funcs or id(fi) in self._built:
                continue
            funcs[id(fi)] = fi
            stack.extend(self.idx.callees_scoped(fi))
        if not funcs:
            return
        succ = {nid: [id(c) for c in self.idx.callees_scoped(fi)
                      if id(c) in funcs or id(c) in self._built]
                for nid, fi in funcs.items()}
        # traversal stays inside this pass; edges into already-built
        # nodes survive in ``succ`` for the union step below
        trav = {nid: [c for c in cs if c in funcs]
                for nid, cs in succ.items()}
        for scc in _tarjan_sccs(funcs, trav):
            # _tarjan_sccs emits an SCC only after every SCC it can
            # reach — callee unions below are already final
            locks = set()
            for nid in scc:
                locks |= self._direct_locks(funcs[nid])
                for cid in succ[nid]:
                    if cid not in scc:
                        locks |= self._acquired.get(cid, set())
            for nid in scc:
                self._acquired[nid] = locks
                self._built.add(nid)

    def acquired(self, fi):
        """Set of lock keys ``fi`` may acquire, transitively through
        every resolvable callee (order-independent; see _build)."""
        if id(fi) not in self._built:
            self._build([fi])
        return self._acquired.get(id(fi), set())

    def edges(self, functions):
        """{(A, B): [(path, line, via)]} acquisition-order edges over
        the given functions."""
        out = {}

        def add(a, b, path, line, via, ctor_b=None):
            if a == b:
                # re-acquiring the lock you already hold: an RLock (or
                # the RLock-backed default Condition; semaphores admit
                # multiple holders) is fine — a plain Lock is a
                # guaranteed self-deadlock and keeps the edge, which
                # _cycles reports as a one-lock cycle.  An UNKNOWN
                # ctor (unresolved ra11-lock annotation) is dropped
                # too: self-deadlock is only ever claimed when the
                # non-reentrant constructor is proven.
                ctor = ctor_b or self.ctors.get(a)
                if ctor is None or ctor in REENTRANT_CTORS:
                    return
            out.setdefault((a, b), []).append((path, line, via))

        for fi in functions:
            for sub in iter_scope(fi.node):
                if not isinstance(sub, (ast.With, ast.AsyncWith)):
                    continue
                held = _with_lock_items(self.idx, fi, sub)
                if not held:
                    continue
                # multiple context managers in one `with a, b:` acquire
                # left-to-right: that order is itself a set of edges
                for i in range(len(held) - 1):
                    for j in range(i + 1, len(held)):
                        add(held[i][0], held[j][0], fi.module.path,
                            sub.lineno, f"{fi.qualname} (with a, b)",
                            ctor_b=held[j][1])
                held_keys = [k for k, _c in held]
                for stmt in sub.body:
                    # same-scope: a callback DEFINED under the lock is
                    # not CALLED under it (deferred execution) — skip
                    # def statements outright (iter_scope only prunes
                    # defs BELOW its root)
                    if isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        continue
                    for inner in iter_scope(stmt):
                        if isinstance(inner, (ast.With, ast.AsyncWith)):
                            for key, ctor in _with_lock_items(
                                    self.idx, fi, inner):
                                for a in held_keys:
                                    add(a, key, fi.module.path,
                                        inner.lineno,
                                        f"{fi.qualname} (nested with)",
                                        ctor_b=ctor)
                        elif isinstance(inner, ast.Call):
                            for callee in self.idx.resolve_call(fi,
                                                                inner):
                                for key in self.acquired(callee):
                                    for a in held_keys:
                                        add(a, key, fi.module.path,
                                            inner.lineno,
                                            f"{fi.qualname} -> "
                                            f"{callee.qualname}()")
        return out


def _tarjan_sccs(nodes, succ):
    """Strongly connected components of a directed graph (iterative
    Tarjan), emitted callees-first: an SCC is appended only after every
    SCC it can reach.  Both consumers (_LockWorld._build's union
    propagation and _cycles) depend on that order — ONE implementation,
    because the lowlink/stack bookkeeping already bit us once (the
    cycle-truncated DFS memo, review round 1)."""
    index = {}
    low = {}
    on_stack = set()
    tstack = []
    sccs = []
    counter = [0]
    for start in nodes:
        if start in index:
            continue
        work = [(start, iter(succ.get(start, ())))]
        index[start] = low[start] = counter[0]
        counter[0] += 1
        tstack.append(start)
        on_stack.add(start)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    tstack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(succ.get(nxt, ()))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = set()
                while True:
                    w = tstack.pop()
                    on_stack.discard(w)
                    scc.add(w)
                    if w == node:
                        break
                sccs.append(scc)
    return sccs


def _cycles(edge_map):
    """Node sets on a lock-order cycle: multi-node SCCs, plus a single
    node with a (non-reentrant, see edges()) self-edge — re-acquiring a
    held plain Lock is a one-lock deadlock."""
    graph = {}
    for (a, b) in edge_map:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    out = []
    for scc in _tarjan_sccs(graph, graph):
        node = next(iter(scc))
        if len(scc) > 1 or node in graph[node]:
            out.append(scc)
    return out


def evaluate_lock_order(idx):
    """RAW RA11 findings: one per acquisition-order edge that sits on a
    lock-order cycle, located at the inner acquisition / call site so
    both directions of an ABBA pair are named."""
    functions = []
    for mod in idx.by_path.values():
        # every indexed package module, not just lint targets — scoped
        # runs evaluate the whole program (see rules._rule_roots)
        if mod.in_tests or not mod.in_package:
            continue
        for defs in mod.func_defs.values():
            functions.extend(defs)
    if not functions:
        return []
    world = _LockWorld(idx)
    world._build(functions)
    edge_map = world.edges(functions)
    out = []
    for scc in _cycles(edge_map):
        # provenance: every edge site on the cycle — linting any ONE
        # of those files must surface both directions of the pair
        site_paths = tuple({path
                            for (a, b), sites in edge_map.items()
                            if a in scc and b in scc
                            for (path, _line, _via) in sites})
        if len(scc) == 1:
            (lone,) = scc
            for path, line, via in edge_map.get((lone, lone), ()):
                out.append(Finding(
                    path, line, "RA11",
                    f"self-deadlock: {lone} re-acquired while already "
                    f"held (via {via}) — a plain threading.Lock is not "
                    "reentrant, so this acquisition blocks its own "
                    "thread forever; use RLock, move the inner work "
                    "outside the lock, or mark the line "
                    "'# ra11-ok: why'", roots=site_paths))
            continue
        cyc = " -> ".join(sorted(scc)) + " -> ..."
        for (a, b), sites in edge_map.items():
            if a in scc and b in scc:
                for path, line, via in sites:
                    out.append(Finding(
                        path, line, "RA11",
                        f"lock-order cycle: {b} acquired while holding "
                        f"{a} (via {via}), but the reverse order also "
                        f"exists on this cycle [{cyc}] — the ABBA "
                        "deadlock class; fix one direction (pre-read "
                        "outside the lock, the _put/_put_batch idiom) "
                        "or mark the line '# ra11-ok: why'",
                        roots=site_paths))
    uniq = {}
    for f in out:
        uniq.setdefault(f.key(), f)
    return list(uniq.values())
