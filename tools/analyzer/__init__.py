"""Whole-program static analyzer for the ra_tpu tree (ISSUE 14).

The package behind ``tools/lint.py``'s closure-gated rules: an AST
index + cross-module call graph (``index``), declarative closure rule
specs evaluated by one shared walker (``rules`` — RA02/RA04/RA08/
RA09/RA10), the RA11 lock-order cycle analyzer (``locks``), the RA12
thread-role/device-sync checker (``threads``), and the suppression
audit (``audit``).  ``run_analysis`` is the one-call entry point
lint.py delegates to; ``report`` renders ``--report``/``--json``.

Design contract: the engine only follows PROVABLE edges (imports,
``self`` methods + MRO, annotated parameters/returns, constructor
assignments, ``# ra-type:`` hints) — see index.py's docstring and
docs/INTERNALS.md §15 for the resolution rules and their documented
limitations.
"""
from __future__ import annotations

from .audit import apply_suppressions, audit_suppressions
from .index import PackageIndex, build_index
from .jitplane import (evaluate_donation, evaluate_schema,
                       evaluate_trace_hazards)
from .locks import evaluate_lock_order
from .rules import (CLOSURE_RULES, Finding, evaluate_closure_rules,
                    evaluate_file_rules)
from .threads import evaluate_thread_roles

__all__ = ["Finding", "PackageIndex", "build_index", "run_analysis",
           "CLOSURE_RULES", "apply_suppressions", "audit_suppressions",
           "evaluate_closure_rules", "evaluate_lock_order",
           "evaluate_thread_roles", "evaluate_trace_hazards",
           "evaluate_donation", "evaluate_schema",
           "evaluate_file_rules"]


def run_analysis(targets, repo=None, default_sources=None):
    """Index the targets (plus what they resolve into) and evaluate
    every engine rule.  Returns ``(raw_findings, index)`` — RAW means
    unsuppressed; the caller merges with its local per-file findings
    and applies :func:`apply_suppressions` / :func:`audit_suppressions`
    over the combined pool so one tag system covers both layers."""
    idx = build_index(targets, repo=repo, default_sources=default_sources)
    raw = []
    raw.extend(evaluate_closure_rules(idx))
    raw.extend(evaluate_lock_order(idx))
    raw.extend(evaluate_thread_roles(idx))
    raw.extend(evaluate_trace_hazards(idx))
    raw.extend(evaluate_donation(idx))
    raw.extend(evaluate_schema(idx))
    raw.extend(evaluate_file_rules(idx, repo=repo))
    return raw, idx
