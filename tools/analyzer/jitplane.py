"""RA13/RA14/RA15 — the jit-plane analyzer (ISSUE 15 tentpole).

The jit boundary is the one plane the ISSUE 14 engine did not see, and
CHANGES.md records a shipped bug in every hazard class gated here: the
PR 6 donation trip ("donate same buffer twice" on shared ``zeros()``
leaves), the PR 6 ``mesh.state_shardings`` rejection of a pytree field
the shardings tree-map didn't cover, the PR 6 ``restore()`` KeyError on
pre-telemetry checkpoints, and the host-sync classes PRs 5/11 found by
review.  Three rule families run over the cross-module index
(tools/analyzer/index.py):

**Traced-closure harvest.**  Roots are the functions that reach a jit
entry point: first args of ``jax.jit(...)`` / ``pjit(...)`` calls and
``@jax.jit``-style decorators (including ``functools.partial(jax.jit,
static_argnames=...)``), plus the body/branch callables of
``lax.scan`` / ``cond`` / ``while_loop`` / ``fori_loop`` / ``switch``
/ ``associative_scan``.  A jitted expression that resolves to a
PARAMETER (the ``_build_jit(fn, ...)`` wrapper idiom) is chased to the
wrapper's call sites and the matching argument resolved there.  The
closure additionally expands resolved METHOD callees to their indexed
subclass overrides (``machine.jit_apply`` statically resolves to the
JitMachine base; the machines actually traced are the overrides) —
an over-approximation that only ever ADDS functions to the traced
world, which is the safe direction for a hazard gate.

**RA13 trace-hazard.**  Inside traced closures: Python ``if``/
``while``/``assert`` on tracer-typed values, host-world calls
(``time.*``/``random.*``/``print``/``open``, ``np.*`` over traced
values), and ``.item()``/``float()``/``int()``/``bool()`` casts of
traced values.  Tracer typing is proof-only: POSITIONAL params of a
traced function are tracers (keyword-only params are the static-config
idiom every jitted fn here uses, and names listed in the jit site's
``static_argnames``/``static_argnums`` are static too); locals are
tracers when assigned from ``jnp.``/``lax.``/``jax.``-rooted calls or
expressions over tracer names.  ``.shape``/``.ndim``/``.dtype``/
``.size`` reads and the flagged casts themselves yield HOST values and
stop propagation (so ``concrete = bool(pred)`` marks only the probe,
not everything downstream — the sanctioned ``cond_concrete`` shape
carries one ``# ra13-ok`` on the probe line).

**RA14 donation-lifetime.**  Donation-enabled jitted callables are
discovered from ``jax.jit(..., donate_argnums=...)`` sites — directly
assigned, or returned by a factory (``_build_jit``) whose result is
stored on an attribute; a conditional ``donate_argnums=(0,) if d else
()`` counts as donating (the gate is for the enabled path).  At every
call site: a read of the donated argument expression AFTER the call,
with no rebinding in between, is flagged — donation invalidates the
buffer, and the read returns poison on backends where donation is real
(``self.state, _ = self._step(self.state, ...)`` rebinds and is the
sanctioned shape).  The second half is the exact PR 6 bug as a rule: a
NamedTuple pytree construction where two leaves are the SAME buffer
binding (one ``z = jnp.zeros(...)`` passed as two fields, or a
``*(z for _ in fields)`` splat of one binding) aliases one device
buffer N ways and trips the donating path's "donate same buffer
twice"; one constructor call per leaf is the fix shape.

**RA15 pytree/sharding/checkpoint schema.**  The state pytree schema
is derived from the construction site: the NamedTuple class annotating
``state_shardings``'s state parameter (cross-module).  Three
contracts: (a) every schema field is covered by the shardings
dispatch — generically (an iteration over ``<Class>._fields``) or by
name; a field the tree-map does not cover is the PR 6 ``device_put``
rejection one mesh boot later, and a by-name special case naming a
NON-field is a stale dispatch arm; (b) the schema module's
``CHECKPOINT_FIELD_DEFAULTS`` registry names every field (and nothing
else), and ``restore()`` consults it — so a checkpoint written before
a field existed restores with the field's declared default instead of
stranding a durable dir (the PR 6 KeyError, generalized to every
future field); (c) every staged superstep-block key
(``shardings.get("n_new")`` in the dispatch-ahead staging path) exists
in ``superstep_block_shardings``'s dict — a staged block with no
matching sharding repartitions on every dispatch (the SNIPPETS.md pjit
rule) or rejects outright on a mesh.

Scope: package code only, tests exempt (same boundary as RA12 —
harnesses drive jits from ad-hoc shapes on purpose).  Findings are RAW;
``# ra13-ok``/``# ra14-ok``/``# ra15-ok`` line tags allowlist, and the
ISSUE 14 audit keeps the tags from rotting.
"""
from __future__ import annotations

import ast

from .index import iter_scope, root_name as _root_name
from .rules import Finding

__all__ = ["evaluate_trace_hazards", "evaluate_donation",
           "evaluate_schema", "harvest_traced"]

#: callables whose N-th positional args are traced function refs.
#: ``switch`` takes its branches as ONE sequence argument
#: (``switch(index, branches, *operands)``) — the resolver unpacks
#: list/tuple literals, so each element roots; naming tail positions
#: here instead would treat data operands as callables (review
#: finding: bogus param sinks chased from operand args)
_TRACE_BODY_FNS = {
    "scan": (0,),
    "cond": (1, 2),
    "while_loop": (0, 1),
    "fori_loop": (2,),
    "associative_scan": (0,),
    "switch": (1,),
}
_JIT_NAMES = frozenset({"jit", "pjit"})
_DEVICE_ROOTS = frozenset({"jnp", "lax", "jax"})
_CAST_FNS = frozenset({"bool", "int", "float", "complex"})
_HOST_MODULES = frozenset({"time", "random"})
#: attribute reads that yield HOST data even off a tracer
_STATIC_ATTRS = frozenset({"shape", "ndim", "dtype", "size"})
#: jnp/jax constructors that allocate (or re-view) one device buffer —
#: the RA14 aliasing half keys on bindings to these
_BUFFER_CTORS = frozenset({"zeros", "ones", "full", "empty", "arange",
                           "zeros_like", "ones_like", "full_like",
                           "empty_like", "broadcast_to"})


def _dotted(expr):
    """'self.state' / 'x' for Name/Attribute chains, else None."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_call(call):
    """True when ``call`` is jax.jit(...)/pjit(...)/jit(...)."""
    fn = call.func
    if isinstance(fn, ast.Name) and fn.id in _JIT_NAMES:
        return True
    if isinstance(fn, ast.Attribute) and fn.attr in _JIT_NAMES and \
            _root_name(fn) in ("jax", "pjit"):
        return True
    return False


def _static_param_names(call):
    """Names pinned static at a jit site (static_argnames / argnums are
    resolved by the caller for argnums; names here)."""
    out = set()
    nums = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    out.add(e.value)
        elif kw.arg == "static_argnums":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    nums.add(e.value)
    return out, nums


class _SynthFunc:
    """FuncInfo-shaped wrapper for a traced Lambda (index FuncInfos only
    cover ``def``s)."""

    __slots__ = ("name", "qualname", "module", "node", "cls")

    def __init__(self, module, node, cls):
        self.name = "<lambda>"
        self.qualname = "<lambda>"
        self.module = module
        self.node = node
        self.cls = cls


def _resolve_traced_expr(idx, fi, expr, sinks):
    """FuncInfos a traced-callable expression may denote.  A parameter
    reference is recorded in ``sinks`` as (fi, param_name) for the
    caller-side chase."""
    out = []
    if isinstance(expr, (ast.List, ast.Tuple)):
        # a sequence of branch callables (lax.switch's second arg)
        for el in expr.elts:
            out.extend(_resolve_traced_expr(idx, fi, el, sinks))
        return out
    if isinstance(expr, ast.Lambda):
        return [_SynthFunc(fi.module, expr, fi.cls)]
    if isinstance(expr, ast.Call):
        # functools.partial(F, ...) — the partial's target is traced
        fn = expr.func
        name = fn.attr if isinstance(fn, ast.Attribute) else \
            fn.id if isinstance(fn, ast.Name) else None
        if name == "partial" and expr.args:
            return _resolve_traced_expr(idx, fi, expr.args[0], sinks)
        return []
    if isinstance(expr, ast.Name):
        params = _positional_params(fi.node)
        if expr.id in params:
            sinks.add((id(fi), fi, expr.id))
            return []
        # prefer a def nested inside this function's own body
        for d in fi.module.func_defs.get(expr.id, []):
            out.append(d)
        if out:
            return out
        got = idx.resolve_name(fi.module, expr.id)
        if got and got[0] == "func":
            return [got[1]]
        return []
    if isinstance(expr, ast.Attribute):
        base = expr.value
        if isinstance(base, ast.Name) and base.id == "self" and \
                fi.cls is not None:
            m = idx.find_method(fi.cls, expr.attr)
            return [m] if m is not None else []
        if isinstance(base, ast.Name):
            got = idx.resolve_name(fi.module, base.id)
            if got and got[0] == "module":
                got2 = idx.resolve_name(got[1], expr.attr)
                if got2 and got2[0] == "func":
                    return [got2[1]]
            elif got and got[0] == "class":
                m = idx.find_method(got[1], expr.attr)
                return [m] if m is not None else []
    return []


def _positional_params(fn_node):
    args = getattr(fn_node, "args", None)
    if args is None:      # a Module pseudo-scope has no parameters
        return []
    return [a.arg for a in list(args.posonlyargs) + list(args.args)]


def _local_partial_target(fi, name):
    """RHS expr when ``name = functools.partial(X, ...)``-style binding
    exists in ``fi`` (the _build_jit idiom: partial built locally, then
    jitted)."""
    for sub in ast.walk(fi.node):
        if isinstance(sub, ast.Assign) and len(sub.targets) == 1 and \
                isinstance(sub.targets[0], ast.Name) and \
                sub.targets[0].id == name:
            return sub.value
    return None


def harvest_traced(idx):
    """{id: (func, origin)} — the traced world: every function the
    resolver can prove reaches a jit/pjit entry point or a control-flow
    primitive body, with the ``"file.py:line"`` origin of the entry
    point that roots it."""
    roots = []            # (func_like, origin string)
    sinks = set()         # (id(fi), fi, param_name): chase call sites

    def _add_site(fi, call, exprs, origin):
        static_names, static_nums = _static_param_names(call)
        for e in exprs:
            if isinstance(e, ast.Name):
                # a local bound to functools.partial(...) one line up
                bound = _local_partial_target(fi, e.id)
                if isinstance(bound, ast.Call):
                    e = bound
            for target in _resolve_traced_expr(idx, fi, e, sinks):
                roots.append((target, origin, static_names, static_nums))

    for mod in idx.by_path.values():
        if mod.in_tests or not mod.in_package:
            continue
        # function-body sites, plus module-level ones (a top-level
        # ``STEP = jax.jit(_step)`` roots _step too) via a Module
        # pseudo-scope — dedup below makes the overlap harmless
        scopes = [fi for defs in mod.func_defs.values() for fi in defs]
        scopes.append(_SynthFunc(mod, mod.tree, None))
        for fi in scopes:
            for sub in ast.walk(fi.node):
                if not isinstance(sub, ast.Call):
                    continue
                origin = f"{mod.stem}.py:{sub.lineno}"
                if _is_jit_call(sub) and sub.args:
                    _add_site(fi, sub, [sub.args[0]], origin)
                    continue
                fn = sub.func
                name = fn.attr if isinstance(fn, ast.Attribute) \
                    else fn.id if isinstance(fn, ast.Name) else None
                if name in _TRACE_BODY_FNS and (
                        not isinstance(fn, ast.Attribute)
                        or _root_name(fn) in ("jax", "lax")):
                    exprs = [sub.args[i]
                             for i in _TRACE_BODY_FNS[name]
                             if i < len(sub.args)]
                    _add_site(fi, sub, exprs, origin)
        # decorator form: @jax.jit / @functools.partial(jax.jit, ...)
        for defs in mod.func_defs.values():
            for fi in defs:
                for dec in getattr(fi.node, "decorator_list", []):
                    call = dec if isinstance(dec, ast.Call) else None
                    statics, nums = (set(), set())
                    if call is not None and _is_jit_call(call):
                        statics, nums = _static_param_names(call)
                    elif call is not None:
                        dfn = call.func
                        dname = dfn.attr if isinstance(dfn, ast.Attribute) \
                            else dfn.id if isinstance(dfn, ast.Name) else None
                        if dname == "partial" and call.args and \
                                isinstance(call.args[0], (ast.Name,
                                                          ast.Attribute)) \
                                and _is_jit_call(ast.Call(
                                    func=call.args[0], args=[],
                                    keywords=[])):
                            statics, nums = _static_param_names(call)
                        else:
                            continue
                    elif not (isinstance(dec, (ast.Name, ast.Attribute))
                              and _is_jit_call(ast.Call(func=dec, args=[],
                                                        keywords=[]))):
                        continue
                    roots.append((fi, f"{mod.stem}.py:{fi.node.lineno}",
                                  statics, nums))

    # chase parameter sinks: a jit wrapper's fn param resolves at the
    # wrapper's call sites (self._build_jit(_step, ...))
    chased = set()
    rounds = 0
    while sinks - chased and rounds < 4:
        rounds += 1
        todo = sinks - chased
        chased |= todo
        for (_sid, sink_fi, pname) in list(todo):
            params = _positional_params(sink_fi.node)
            p_idx = params.index(pname) if pname in params else -1
            if p_idx < 0:
                continue
            for mod in idx.by_path.values():
                if mod.in_tests:
                    continue
                for defs in mod.func_defs.values():
                    for caller in defs:
                        for sub in ast.walk(caller.node):
                            if not isinstance(sub, ast.Call):
                                continue
                            if not any(c is sink_fi for c in
                                       idx.resolve_call(caller, sub)):
                                continue
                            # bound-method calls drop self
                            off = p_idx - 1 if (
                                sink_fi.cls is not None and
                                isinstance(sub.func, ast.Attribute)) \
                                else p_idx
                            arg = None
                            if 0 <= off < len(sub.args):
                                arg = sub.args[off]
                            for kw in sub.keywords:
                                if kw.arg == pname:
                                    arg = kw.value
                            if arg is None:
                                continue
                            origin = f"{mod.stem}.py:{sub.lineno}"
                            for target in _resolve_traced_expr(
                                    idx, caller, arg, sinks):
                                roots.append((target, origin,
                                              set(), set()))

    # transitive closure + subclass-override expansion
    out = {}
    queue = list(roots)
    override_memo = {}
    while queue:
        fi, origin, statics, nums = queue.pop(0)
        if id(fi) in out:
            continue
        out[id(fi)] = (fi, origin, statics, nums)
        callees = idx.callees(fi) if not isinstance(fi, _SynthFunc) \
            else _lambda_callees(idx, fi)
        for callee in callees:
            queue.append((callee, origin, set(), set()))
            for ov in _overrides(idx, callee, override_memo):
                queue.append((ov, origin, set(), set()))
    return out


def _lambda_callees(idx, sfi):
    out = []
    seen = set()
    for sub in ast.walk(sfi.node):
        if isinstance(sub, ast.Call):
            for callee in idx.resolve_call(sfi, sub):
                if id(callee) not in seen:
                    seen.add(id(callee))
                    out.append(callee)
    return out


def _overrides(idx, fi, memo):
    """Indexed subclass overrides of a resolved method — the traced
    world's stand-in for virtual dispatch (jit_apply on the JitMachine
    base resolves, the machines traced in production are overrides)."""
    if fi.cls is None or fi.name.startswith("__"):
        return []
    got = memo.get(id(fi))
    if got is not None:
        return got
    out = []
    for mod in idx.by_path.values():
        if mod.in_tests:
            continue
        for ci in mod.classes.values():
            if ci is fi.cls:
                continue
            m = ci.methods.get(fi.name)
            if m is not None and m is not fi and fi.cls in idx.mro(ci):
                out.append(m)
    memo[id(fi)] = out
    return out


# -- RA13: trace hazards ---------------------------------------------------

def _tracer_names(fi, static_names, static_nums):
    """Proof-only tracer typing for one traced function: positional
    params (minus self/statics), plus locals derived from device calls
    or other tracer names; casts and shape reads stop propagation."""
    params = _positional_params(fi.node) if not isinstance(
        fi.node, ast.Lambda) else [a.arg for a in fi.node.args.args]
    traced = set()
    for i, p in enumerate(params):
        if p in ("self", "cls") or p in static_names or i in static_nums:
            continue
        traced.add(p)
    # keyword-only params are the static-config idiom: never tracers
    for _ in range(3):
        changed = False
        for sub in ast.walk(fi.node):
            value = None
            targets = []
            if isinstance(sub, ast.Assign):
                value, targets = sub.value, sub.targets
            elif isinstance(sub, ast.AugAssign):
                value, targets = sub.value, [sub.target]
            elif isinstance(sub, ast.AnnAssign) and sub.value is not None:
                value, targets = sub.value, [sub.target]
            if value is None or not _expr_traced(value, traced):
                continue
            for t in targets:
                for el in ast.walk(t):
                    if isinstance(el, ast.Name) and el.id not in traced:
                        traced.add(el.id)
                        changed = True
        if not changed:
            break
    return traced


def _expr_traced(expr, traced):
    """Does ``expr`` (or any reachable subexpression) carry a tracer?
    Stops at .shape/.ndim/.dtype/.size reads and host casts — those
    yield concrete host values."""
    if isinstance(expr, ast.Attribute) and expr.attr in _STATIC_ATTRS:
        return False
    if isinstance(expr, ast.Call):
        fn = expr.func
        if isinstance(fn, ast.Name) and fn.id in _CAST_FNS:
            return False
        if isinstance(fn, ast.Attribute) and fn.attr == "item":
            return False
        if _root_name(fn) in _DEVICE_ROOTS:
            return True
        # a method call ON a tracer yields a tracer (state.sum())
        if isinstance(fn, ast.Attribute) and \
                _expr_traced(fn.value, traced):
            return True
        return any(_expr_traced(a, traced) for a in expr.args) or \
            any(_expr_traced(kw.value, traced) for kw in expr.keywords)
    if isinstance(expr, ast.Name):
        return expr.id in traced
    return any(_expr_traced(c, traced)
               for c in ast.iter_child_nodes(expr)
               if isinstance(c, ast.expr))


def evaluate_trace_hazards(idx):
    """RAW RA13 findings over the traced world."""
    out = []
    for fi, origin, statics, nums in harvest_traced(idx).values():
        mod = fi.module
        if mod.in_tests or not mod.in_package:
            continue
        traced = _tracer_names(fi, statics, nums)
        ctx = f"traced closure {fi.name}() (traced via {origin})"
        tail = ("— data-dependent Python control flow concretizes a "
                "tracer and fails (or silently specializes) under jit; "
                "use lax.cond/where or mark the line '# ra13-ok: why'")
        for sub in iter_scope(fi.node):
            if isinstance(sub, (ast.If, ast.While)) and \
                    _expr_traced(sub.test, traced):
                kind = "if" if isinstance(sub, ast.If) else "while"
                out.append(Finding(
                    mod.path, sub.lineno, "RA13",
                    f"Python `{kind}` on a traced value in {ctx} "
                    + tail, roots=(mod.path,)))
            elif isinstance(sub, ast.Assert) and \
                    _expr_traced(sub.test, traced):
                out.append(Finding(
                    mod.path, sub.lineno, "RA13",
                    f"`assert` on a traced value in {ctx} — asserts "
                    "vanish under tracing (checked once at trace time, "
                    "never per step); use checkify or host-side "
                    "validation, or mark the line '# ra13-ok: why'",
                    roots=(mod.path,)))
            elif isinstance(sub, ast.Call):
                out.extend(_call_hazards(mod, fi, sub, traced, ctx))
    uniq = {}
    for f in out:
        uniq.setdefault(f.key(), f)
    return list(uniq.values())


def _call_hazards(mod, fi, call, traced, ctx):
    out = []
    fn = call.func
    root = _root_name(fn) if isinstance(fn, ast.Attribute) else None
    name = fn.id if isinstance(fn, ast.Name) else None
    if name in _CAST_FNS and any(_expr_traced(a, traced)
                                 for a in call.args):
        out.append(Finding(
            mod.path, call.lineno, "RA13",
            f"{name}() cast of a traced value in {ctx} — the cast "
            "forces concretization (TracerBoolConversionError under "
            "jit); keep the value symbolic or mark the line "
            "'# ra13-ok: why'", roots=(mod.path,)))
    elif name in ("print", "open"):
        out.append(Finding(
            mod.path, call.lineno, "RA13",
            f"host-world call {name}() in {ctx} — side effects inside "
            "a traced closure run at TRACE time only (once per "
            "compile, never per step); hoist to the host caller or "
            "mark the line '# ra13-ok: why'", roots=(mod.path,)))
    elif root in _HOST_MODULES:
        out.append(Finding(
            mod.path, call.lineno, "RA13",
            f"host-world call {root}.{fn.attr}() in {ctx} — wall-clock "
            "and host RNG freeze at trace time (one value baked into "
            "the compiled step); thread them in as operands or mark "
            "the line '# ra13-ok: why'", roots=(mod.path,)))
    elif root == "np" and any(_expr_traced(a, traced)
                              for a in call.args):
        out.append(Finding(
            mod.path, call.lineno, "RA13",
            f"np.{fn.attr}() over a traced value in {ctx} — numpy "
            "concretizes the tracer (a device sync at best, a trace "
            "error at worst); use jnp or mark the line "
            "'# ra13-ok: why'", roots=(mod.path,)))
    elif isinstance(fn, ast.Attribute) and fn.attr == "item" and \
            not call.args and _expr_traced(fn.value, traced):
        out.append(Finding(
            mod.path, call.lineno, "RA13",
            f".item() on a traced value in {ctx} — concretization "
            "error under jit; return it as an output instead or mark "
            "the line '# ra13-ok: why'", roots=(mod.path,)))
    return out


# -- RA14: donation lifetime -----------------------------------------------

def _donated_positions(call):
    """Set of donated positional indexes at a jax.jit site; a
    conditional ``(0,) if donate else ()`` contributes both arms (the
    gate polices the donation-ENABLED path)."""
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        vals = [kw.value]
        out = set()
        while vals:
            v = vals.pop()
            if isinstance(v, ast.IfExp):
                vals.extend([v.body, v.orelse])
            elif isinstance(v, (ast.Tuple, ast.List)):
                vals.extend(v.elts)
            elif isinstance(v, ast.Constant) and isinstance(v.value, int):
                out.add(v.value)
        return out
    return set()


def _donating_factories(idx):
    """{id(fi): positions} for functions returning a donating jit."""
    out = {}
    for mod in idx.by_path.values():
        if mod.in_tests:
            continue
        for defs in mod.func_defs.values():
            for fi in defs:
                for sub in ast.walk(fi.node):
                    if isinstance(sub, ast.Return) and \
                            isinstance(sub.value, ast.Call) and \
                            _is_jit_call(sub.value):
                        pos = _donated_positions(sub.value)
                        if pos:
                            out.setdefault(id(fi), set()).update(pos)
    return out


def _donating_bindings(idx, factories):
    """attr bindings: {(id(ClassInfo), attr): positions} for
    ``self.attr = jax.jit(..., donate_argnums=...)`` or
    ``self.attr = self._factory(...)``."""
    attrs = {}
    for mod in idx.by_path.values():
        if mod.in_tests:
            continue
        for ci in mod.classes.values():
            for m in ci.methods.values():
                for sub in ast.walk(m.node):
                    if not (isinstance(sub, ast.Assign) and
                            len(sub.targets) == 1):
                        continue
                    t = sub.targets[0]
                    if not (isinstance(t, ast.Attribute) and
                            isinstance(t.value, ast.Name) and
                            t.value.id == "self"):
                        continue
                    v = sub.value
                    pos = set()
                    if isinstance(v, ast.Call) and _is_jit_call(v):
                        pos = _donated_positions(v)
                    elif isinstance(v, ast.Call):
                        for callee in idx.resolve_call(m, v):
                            pos |= factories.get(id(callee), set())
                    if pos:
                        attrs.setdefault((id(ci), t.attr),
                                         set()).update(pos)
    return attrs


def _assign_target_keys(node):
    """Dotted keys stored by an assignment statement's targets."""
    out = set()
    targets = []
    if isinstance(node, ast.Assign):
        targets = node.targets
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        targets = [node.target]
    for t in targets:
        for el in ast.walk(t):
            key = _dotted(el)
            if key:
                out.add(key)
    return out


def evaluate_donation(idx):
    """RAW RA14 findings: donated-buffer reads after the donating call,
    and pytree constructions aliasing one buffer across leaves."""
    factories = _donating_factories(idx)
    attrs = _donating_bindings(idx, factories)
    out = []
    for mod in idx.by_path.values():
        if mod.in_tests or not mod.in_package:
            continue
        # module-level donating names: STEP = jax.jit(f, donate_...)
        mod_donating = {}
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and \
                    len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name) and \
                    isinstance(node.value, ast.Call) and \
                    _is_jit_call(node.value):
                pos = _donated_positions(node.value)
                if pos:
                    mod_donating[node.targets[0].id] = pos
        for defs in mod.func_defs.values():
            for fi in defs:
                out.extend(_donated_read_findings(idx, fi, attrs,
                                                  mod_donating))
                out.extend(_aliased_leaf_findings(idx, fi))
    uniq = {}
    for f in out:
        uniq.setdefault(f.key(), f)
    return list(uniq.values())


def _donated_read_findings(idx, fi, attrs, mod_donating=None):
    out = []
    mod = fi.module
    # local donating names: x = jax.jit(..., donate_argnums=...)
    local = dict(mod_donating or {})
    for sub in ast.walk(fi.node):
        if isinstance(sub, ast.Assign) and len(sub.targets) == 1 and \
                isinstance(sub.targets[0], ast.Name) and \
                isinstance(sub.value, ast.Call) and \
                _is_jit_call(sub.value):
            pos = _donated_positions(sub.value)
            if pos:
                local[sub.targets[0].id] = pos
    # events: (lineno, key) stores from assignments, loads from
    # name/attr reads — SAME-SCOPE only (iter_scope): a rebind inside
    # a nested def is deferred execution and must not mask a real
    # post-donation read in the enclosing scope (review finding)
    stores = []
    for sub in iter_scope(fi.node):
        if isinstance(sub, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            for key in _assign_target_keys(sub):
                stores.append((sub.lineno, key))
    loops = [n for n in iter_scope(fi.node)
             if isinstance(n, (ast.For, ast.AsyncFor, ast.While))]
    for sub in iter_scope(fi.node):
        if not isinstance(sub, ast.Call):
            continue
        fn = sub.func
        pos = set()
        via = None
        if isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name) and \
                fn.value.id == "self" and fi.cls is not None:
            for anc in idx.mro(fi.cls):
                pos |= attrs.get((id(anc), fn.attr), set())
            via = f"self.{fn.attr}"
        elif isinstance(fn, ast.Name) and fn.id in local:
            pos = local[fn.id]
            via = fn.id
        if not pos:
            continue
        for p in sorted(pos):
            if p >= len(sub.args):
                continue
            key = _dotted(sub.args[p])
            if key is None:
                continue
            # loop-carried donation: a donating call INSIDE a loop
            # with no rebind of the donated key anywhere in the loop
            # hands the invalidated buffer back to the call on the
            # next iteration — a read the linear before/after scan
            # cannot see (review finding)
            containing = [lp for lp in loops
                          if any(n is sub for n in ast.walk(lp))]
            if containing:
                # the INNERMOST containing loop decides: a rebind in
                # its body runs every iteration and protects all
                # enclosing loops too
                inner = max(containing, key=lambda lp: lp.lineno)
                rebound = any(
                    key in _assign_target_keys(n)
                    for n in iter_scope(inner)
                    if isinstance(n, (ast.Assign, ast.AugAssign,
                                      ast.AnnAssign)))
                if not rebound:
                    out.append(Finding(
                        mod.path, sub.lineno, "RA14",
                        f"`{key}` is DONATED to {via}(...) inside a "
                        "loop that never rebinds it — the next "
                        "iteration passes the invalidated buffer "
                        "back in; rebind the result "
                        "(`x, aux = f(x, ...)`) or mark the line "
                        "'# ra14-ok: why'", roots=(mod.path,)))
            first_store = min((ln for ln, k in stores
                               if k == key and ln >= sub.lineno),
                              default=None)
            # earliest same-scope read AFTER the donating call (sorted
            # — ast order is not line order, and a post-rebind read
            # visited first would mask an earlier pre-rebind one)
            first_load = min(
                (load.lineno for load in iter_scope(fi.node)
                 if isinstance(load, (ast.Name, ast.Attribute))
                 and _dotted(load) == key
                 and load.lineno > sub.lineno),
                default=None)
            if first_load is not None and (
                    first_store is None or first_load < first_store):
                out.append(Finding(
                    mod.path, first_load, "RA14",
                    f"read of `{key}` after it was DONATED to "
                    f"{via}(...) at line {sub.lineno} — donation "
                    "invalidates the buffer (poison on backends where "
                    "donation is real); rebind the result "
                    "(`x, aux = f(x, ...)`) before any further read, "
                    "or mark the line '# ra14-ok: why'",
                    roots=(mod.path,)))
    return out


def _is_namedtuple_class(idx, ci):
    for b in ci.base_exprs:
        name = b.id if isinstance(b, ast.Name) else \
            b.attr if isinstance(b, ast.Attribute) else None
        if name == "NamedTuple":
            return True
        base = idx.resolve_type(ci.module, b)
        if base is not None and base is not ci and \
                _is_namedtuple_class(idx, base):
            return True
    return False


def _buffer_bound_keys(idx, fi):
    """Dotted keys in ``fi``'s scope bound to a single device-buffer
    constructor call (jnp.zeros(...) and friends)."""
    out = set()
    for sub in ast.walk(fi.node):
        if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
            v = sub.value
            if isinstance(v, ast.Call) and \
                    isinstance(v.func, ast.Attribute) and \
                    v.func.attr in _BUFFER_CTORS and \
                    _root_name(v.func) in _DEVICE_ROOTS:
                key = _dotted(sub.targets[0])
                if key:
                    out.add(key)
    if fi.cls is not None:
        for m in fi.cls.methods.values():
            for sub in ast.walk(m.node):
                if isinstance(sub, ast.Assign) and \
                        len(sub.targets) == 1:
                    v = sub.value
                    t = sub.targets[0]
                    if isinstance(v, ast.Call) and \
                            isinstance(v.func, ast.Attribute) and \
                            v.func.attr in _BUFFER_CTORS and \
                            _root_name(v.func) in _DEVICE_ROOTS and \
                            isinstance(t, ast.Attribute):
                        key = _dotted(t)
                        if key:
                            out.add(key)
    return out


def _aliased_leaf_findings(idx, fi):
    out = []
    mod = fi.module
    buffers = None
    for sub in ast.walk(fi.node):
        if not isinstance(sub, ast.Call):
            continue
        fn = sub.func
        target = None
        if isinstance(fn, ast.Name):
            got = idx.resolve_name(mod, fn.id)
            if got and got[0] == "class":
                target = got[1]
        if target is None or not _is_namedtuple_class(idx, target):
            continue
        if buffers is None:
            buffers = _buffer_bound_keys(idx, fi)
        seen = {}
        values = list(sub.args) + [kw.value for kw in sub.keywords]
        for v in values:
            if isinstance(v, ast.Starred):
                inner = v.value
                elt = inner.elt if isinstance(
                    inner, (ast.GeneratorExp, ast.ListComp)) else None
                key = _dotted(elt) if elt is not None else None
                if key is not None and key in buffers:
                    out.append(Finding(
                        mod.path, sub.lineno, "RA14",
                        f"pytree {target.name}(...) splats ONE buffer "
                        f"binding `{key}` across every leaf — the "
                        "leaves alias one device buffer, and the "
                        "donating superstep path rejects a donated "
                        "buffer appearing twice in an Execute() (the "
                        "PR 6 shared-zeros() bug); construct one "
                        "fresh buffer per leaf or mark the line "
                        "'# ra14-ok: why'", roots=(mod.path,)))
                continue
            key = _dotted(v)
            if key is None:
                continue
            if key in seen and key in buffers:
                out.append(Finding(
                    mod.path, sub.lineno, "RA14",
                    f"pytree {target.name}(...) passes buffer binding "
                    f"`{key}` as two leaves — aliased leaves share one "
                    "device buffer and trip donation ('donate same "
                    "buffer twice'); construct one buffer per leaf or "
                    "mark the line '# ra14-ok: why'",
                    roots=(mod.path,)))
            seen[key] = True
    return out


# -- RA15: pytree / sharding / checkpoint schema ---------------------------

def _namedtuple_fields(ci):
    return [stmt.target.id for stmt in ci.node.body
            if isinstance(stmt, ast.AnnAssign)
            and isinstance(stmt.target, ast.Name)]


def _schema_from_shardings_fn(idx, fi):
    """The schema class annotating state_shardings' state param."""
    args = fi.node.args
    pos = list(args.posonlyargs) + list(args.args)
    for a in pos:
        if a.arg in ("self", "mesh"):
            continue
        if a.annotation is not None:
            ci = idx.resolve_type(fi.module, a.annotation)
            if ci is not None and _is_namedtuple_class(idx, ci):
                return ci
    return None


def _fields_iteration_present(fn_node):
    for sub in ast.walk(fn_node):
        if isinstance(sub, (ast.For, ast.comprehension)):
            it = sub.iter
            if isinstance(it, ast.Attribute) and it.attr == "_fields":
                return True, (sub.target.id if isinstance(
                    sub.target, ast.Name) else None)
    return False, None


def evaluate_schema(idx):
    """RAW RA15 findings for all three schema contracts."""
    out = []
    schemas = {}   # id(ci) -> (ci, discovered-at module path)
    for mod in idx.by_path.values():
        if mod.in_tests or not mod.in_package:
            continue
        for fi in mod.func_defs.get("state_shardings", []):
            ci = _schema_from_shardings_fn(idx, fi)
            if ci is None:
                continue
            schemas.setdefault(id(ci), (ci, mod.path))
            out.extend(_shardings_coverage_findings(fi, ci))
    for ci, via in schemas.values():
        out.extend(_checkpoint_defaults_findings(idx, ci, via))
    out.extend(_block_staging_findings(idx))
    uniq = {}
    for f in out:
        uniq.setdefault(f.key(), f)
    return list(uniq.values())


def _shardings_coverage_findings(fi, ci):
    """(a): every schema field covered by the shardings dispatch."""
    out = []
    mod = fi.module
    fields = set(_namedtuple_fields(ci))
    generic, loop_var = _fields_iteration_present(fi.node)
    consts = set()
    kw_names = set()
    dict_keys = set()
    for sub in ast.walk(fi.node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            consts.add(sub.value)
        elif isinstance(sub, ast.Call):
            for kw in sub.keywords:
                if kw.arg is not None:
                    kw_names.add(kw.arg)
        elif isinstance(sub, ast.Dict):
            for k in sub.keys:
                if isinstance(k, ast.Constant) and \
                        isinstance(k.value, str):
                    dict_keys.add(k.value)
    if generic:
        # stale dispatch arms: a by-name special case must name a field
        if loop_var:
            for sub in ast.walk(fi.node):
                if not isinstance(sub, ast.Compare):
                    continue
                names = {n.id for n in ast.walk(sub)
                         if isinstance(n, ast.Name)}
                if loop_var not in names:
                    continue
                for c in ast.walk(sub):
                    if isinstance(c, ast.Constant) and \
                            isinstance(c.value, str) and \
                            c.value not in fields:
                        out.append(Finding(
                            mod.path, sub.lineno, "RA15",
                            f"state_shardings special-cases "
                            f"{c.value!r}, which is not a field of "
                            f"{ci.name} — a stale dispatch arm (field "
                            "renamed/removed without updating the "
                            "shardings tree-map); drop it or mark the "
                            "line '# ra15-ok: why'",
                            roots=(mod.path, ci.module.path)))
    else:
        covered = consts | kw_names | dict_keys
        missing = sorted(fields - covered)
        if missing:
            out.append(Finding(
                mod.path, fi.node.lineno, "RA15",
                f"state_shardings does not cover {ci.name} field(s) "
                f"{missing[:6]} — an uncovered pytree field makes "
                "device_put reject the sharded state one mesh boot "
                "later (the PR 6 telemetry-field bug); cover every "
                "field (iterate <Class>._fields for generic coverage) "
                "or mark the line '# ra15-ok: why'",
                roots=(mod.path, ci.module.path)))
    return out


def _checkpoint_defaults_findings(idx, ci, via):
    """(b): the schema module's CHECKPOINT_FIELD_DEFAULTS registry
    covers every field, and restore() consults it."""
    out = []
    mod = ci.module
    restores = [fi for fi in mod.func_defs.get("restore", [])]
    if not restores:
        return out
    fields = _namedtuple_fields(ci)
    reg_node = None
    reg_keys = []
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "CHECKPOINT_FIELD_DEFAULTS" and \
                isinstance(node.value, ast.Dict):
            reg_node = node
            reg_keys = [k.value for k in node.value.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)]
    roots = (mod.path, via)
    if reg_node is None:
        out.append(Finding(
            mod.path, ci.node.lineno, "RA15",
            f"{ci.name} has a restore() path but no "
            "CHECKPOINT_FIELD_DEFAULTS registry — without a per-field "
            "default, the next pytree field addition strands every "
            "durable dir behind a checkpoint format bump (the PR 6 "
            "restore() KeyError); declare the registry or mark the "
            "line '# ra15-ok: why'", roots=roots))
        return out
    missing = sorted(set(fields) - set(reg_keys))
    stale = sorted(set(reg_keys) - set(fields))
    if missing:
        out.append(Finding(
            mod.path, reg_node.lineno, "RA15",
            f"CHECKPOINT_FIELD_DEFAULTS is missing {ci.name} "
            f"field(s) {missing[:6]} — an unregistered field has no "
            "restore default, so archives written before it existed "
            "strand their durable dirs; add '<field>: zeros' (or "
            "'require' for fields every archive has always carried) "
            "or mark the line '# ra15-ok: why'", roots=roots))
    if stale:
        out.append(Finding(
            mod.path, reg_node.lineno, "RA15",
            f"CHECKPOINT_FIELD_DEFAULTS names {stale[:6]} which are "
            f"not fields of {ci.name} — a stale registry entry (field "
            "renamed/removed); drop it or mark the line "
            "'# ra15-ok: why'", roots=roots))
    for fi in restores:
        # the registry may be consulted by a helper restore() calls —
        # check the resolvable call closure, not just the def body
        refs = any(
            isinstance(n, ast.Name) and
            n.id == "CHECKPOINT_FIELD_DEFAULTS"
            for member in idx.closure([fi]).values()
            for n in ast.walk(member.node))
        if not refs:
            out.append(Finding(
                mod.path, fi.node.lineno, "RA15",
                f"restore() in {mod.stem}.py does not consult "
                "CHECKPOINT_FIELD_DEFAULTS — a hand-rolled restore "
                "path bypasses the schema defaults and re-opens the "
                "pre-telemetry KeyError class; route missing fields "
                "through the registry or mark the line "
                "'# ra15-ok: why'", roots=roots))
    return out


def _block_staging_findings(idx):
    """(c): every staged superstep-block key has a sharding entry."""
    out = []
    dict_keys = set()
    providers = []
    for mod in idx.by_path.values():
        if mod.in_tests:
            continue
        for fi in mod.func_defs.get("superstep_block_shardings", []):
            providers.append(mod.path)
            for sub in ast.walk(fi.node):
                if isinstance(sub, ast.Dict):
                    for k in sub.keys:
                        if isinstance(k, ast.Constant) and \
                                isinstance(k.value, str):
                            dict_keys.add(k.value)
    if not providers:
        return out
    for mod in idx.by_path.values():
        if mod.in_tests or not mod.in_package:
            continue
        for node in ast.walk(mod.tree):
            key = None
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "get" and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                base = _dotted(node.func.value) or ""
                if "shardings" in base:
                    key = node.args[0].value
            elif isinstance(node, ast.Subscript) and \
                    isinstance(node.slice, ast.Constant) and \
                    isinstance(node.slice.value, str):
                base = _dotted(node.value) or ""
                if "shardings" in base:
                    key = node.slice.value
            if key is not None and key not in dict_keys:
                out.append(Finding(
                    mod.path, node.lineno, "RA15",
                    f"staged superstep-block key {key!r} has no entry "
                    "in superstep_block_shardings — a staged block "
                    "with no matching sharding repartitions on every "
                    "dispatch (or device_put rejects it on a mesh); "
                    "add the entry or mark the line "
                    "'# ra15-ok: why'", roots=tuple(providers)))
    return out
