#!/bin/bash
# TPU tunnel watcher (round 5).
#
# The round-4 review's top item: the moment the axon tunnel is back, capture
# the FULL bench matrix on real TPU (headline counter xla+pallas at 10k x 5,
# fifo 5k x 5, kv 2k, durable mode, frontier p50/p99 sweep) with host
# metadata in every row.  Rows land in $OUT (committed to the repo by the
# session).  Probes every $PROBE_SLEEP seconds for up to $MAX_ATTEMPTS
# attempts (~12h); captures rows in priority order so a tunnel flap
# mid-matrix still leaves the most important rows behind.
cd /root/repo || exit 1
OUT=${RA_TPU_WATCH_OUT:-/root/repo/tpu_rows_r05}
PROBE_SLEEP=${RA_TPU_WATCH_SLEEP:-240}
MAX_ATTEMPTS=${RA_TPU_WATCH_ATTEMPTS:-170}
mkdir -p "$OUT"

capture() {  # capture <name> <timeout> [ENV=VAL ...]
  local name=$1 tmo=$2; shift 2
  echo "$(date +%H:%M:%S) capturing $name" >> "$OUT/log"
  env RA_TPU_BENCH_CHILD=1 "$@" timeout "$tmo" python bench.py \
    > "$OUT/$name.json" 2> "$OUT/$name.err"
  echo "$(date +%H:%M:%S) $name rc=$?" >> "$OUT/log"
}

for attempt in $(seq 1 "$MAX_ATTEMPTS"); do
  if timeout 90 python -c "import jax; assert jax.devices()[0].platform != 'cpu'" \
      >/dev/null 2>&1; then
    echo "$(date +%H:%M:%S) tunnel UP on attempt $attempt" >> "$OUT/log"
    capture headline_xla   600 RA_TPU_QUORUM_IMPL=xla RA_TPU_BENCH_SECONDS=4.0
    capture fifo_5k        600 RA_TPU_BENCH_MACHINE=fifo RA_TPU_BENCH_LANES=5000 \
                               RA_TPU_BENCH_SECONDS=3.0
    capture frontier       600 RA_TPU_BENCH_MODE=frontier RA_TPU_BENCH_SECONDS=3.0
    capture durable        600 RA_TPU_BENCH_DURABLE=1 RA_TPU_BENCH_SECONDS=4.0
    capture kv_2k          600 RA_TPU_BENCH_MACHINE=kv RA_TPU_BENCH_LANES=2000 \
                               RA_TPU_BENCH_SECONDS=3.0
    capture headline_pallas 600 RA_TPU_QUORUM_IMPL=pallas RA_TPU_BENCH_SECONDS=3.0
    # the sharded-mesh frontier sweep (ISSUE 11): only meaningful when
    # the backend exposes >1 real device; the child no-ops the 2x4
    # shape on a single chip but the 1xD ladder still captures
    capture multichip      900 RA_TPU_BENCH_MODE=multichip RA_TPU_BENCH_SECONDS=3.0
    echo "$(date +%H:%M:%S) matrix done" >> "$OUT/log"
    exit 0
  fi
  echo "$(date +%H:%M:%S) probe $attempt down" >> "$OUT/log"
  sleep "$PROBE_SLEEP"
done
echo "$(date +%H:%M:%S) gave up after $MAX_ATTEMPTS attempts" >> "$OUT/log"
exit 2
