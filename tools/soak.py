"""Fuzz soak runner — drives the interleaving fuzz families from
tests/test_props.py over fresh seed ranges (the in-suite parametrize
lists anchor known bug-finding seeds; this explores NEW schedules).

Usage:  python tools/soak.py [seeds_per_family] [offset]
        python tools/soak.py --disk-faults SEED [n]
        python tools/soak.py --superstep SEED [n]
        python tools/soak.py --obs SEED [n] [jsonl_path]
        python tools/soak.py --blackbox SEED [n]
        python tools/soak.py --ingress SEED [n] [--mesh]
        python tools/soak.py --wire SEED [--durable] [--c1m]
        python tools/soak.py --device-obs SEED [n]
        python tools/soak.py --failover SEED [SEED...]
        python tools/soak.py --geo SEED [SEED...]
        python tools/soak.py --reads SEED [n]

``--wire`` climbs the ISSUE 12 connection ladder (ra_tpu/wire/soak.py
run_wire_soak): C10k (with a real-socket side-car) → C100k loopback
connections — add ``--c1m`` for the full C1M rung — each rung the
whole wire path (fixed-stride frames → per-connection rings →
vectorized sweep → ingress → fused dispatch) under a reconnect storm,
election chaos, and a standing transport FaultPlan, closed by the
exactly-once-observable oracle (machine-level dedup).  ``--durable``
adds fsync-gated commits with a seeded DiskFaultPlan.  Prints one
JSON tail per rung carrying ``wire_cmds_per_s``/``wire_shed_rate``/
``wire_reconnect_recovery_s`` for tools/bench_diff.py.

``--ingress`` runs the ISSUE 10 acceptance scenario at FULL scale
(tests/test_ingress.run_ingress_soak): ~1M simulated sessions fanning
into 10k lanes through the session-directory → coalescer →
backpressure-ladder path, with duplicate resends, member-failure/
election chaos and a seeded DiskFaultPlan injecting real WAL faults on
the durable variant — then an exactly-once oracle check (final machine
state == the dedup'd placed set, so no resend applied twice) plus
monotone consistent-read probes.  Prints a one-line JSON tail carrying
``ingress_cmds_per_s``/``ingress_shed_rate`` for tools/bench_diff.py.

``--disk-faults`` runs the storage-plane chaos family instead
(tests/test_disk_faults.run_disk_chaos): ``n`` seeded episodes starting
at SEED, each a random DiskFaultPlan + WAL crash over a live durable
log with a cold-restart oracle check.

``--superstep`` runs the fused-dispatch parity family
(tests/test_superstep.run_superstep_fuzz): ``n`` seeded episodes of
random K/elect schedules + member failures, each exact-parity checked
against the single-step oracle every round (ISSUE 5).

``--blackbox`` runs the flight-recorder chaos family
(tests/test_blackbox.run_blackbox_chaos): ``n`` seeded episodes, each
a classic durable cluster taking traced traffic through a random
DiskFaultPlan, then a kill-9 of the WAL under the ACTIVE plan —
asserting the post-mortem bundle exists, parses, names the injected
fault, and that ``tools/ra_trace.py`` reconstructs the complete
lifecycle (ingress→submit→append→WAL write→fsync→confirm→commit→apply)
of a command the fault touched (ISSUE 7 acceptance).

``--obs`` runs the telemetry-plane chaos family
(tests/test_telemetry.run_stall_chaos): ``n`` seeded episodes that
break a random lane's quorum under traffic and assert the stall is
*detected* by the device-resident telemetry (stalled-lane count +
top-K offenders, within one sampling window), not just recovered —
while every harvested Observatory snapshot is appended to a JSONL
ring (default ``obs.jsonl``; follow it live with
``python tools/ra_top.py <path>``).

``--device-obs`` runs the device-plane observatory chaos family
(tests/test_devicewatch.run_device_obs_chaos, ISSUE 16): ``n`` seeded
episodes, each a DURABLE engine taking fixed-shape superstep traffic
through election churn and a seeded WAL DiskFaultPlan — asserting the
recompile sentinel stays QUIET (host-plane chaos is not shape drift),
then that a deliberate mixed-shape probe (K=8 -> K=4) IS detected
within one Observatory window and attributed to the drifting block
shape.  Engine configs are seed-varied so every episode compiles
fresh jit variants.

``--reads`` runs the linearizable-read oracle family
(tests/test_read_plane.run_read_oracle, ISSUE 20): ``n`` seeded
episodes, each driving BOTH read machines (TtlKvMachine, StreamMachine)
single-device AND on the sharded 8-way lane mesh — plus one durable run
under a seeded WAL DiskFaultPlan — through election churn, leader
kills and majority partitions while a host model machine folds the
same committed history.  Every read the device SERVES must equal the
model's answer over the full committed prefix (a reply matching only
an older prefix is a stale serve, pinned 0); a leader cut from quorum
must REFUSE once its lease expires; healed lanes must serve again.

Prints one line per family with pass/fail counts; exits nonzero on the
first failing seed (which should then be added to the in-suite list).
"""
from __future__ import annotations

import os

# force the CPU backend BEFORE anything imports jax: the engine-chaos
# family pulls in the engine, and on a dead axon tunnel default backend
# init hangs for minutes (same setup as tests/conftest.py)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import sys
import tempfile
import time
import traceback

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tests"))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# a tunnel site hook may have registered a PJRT plugin whose discovery
# blocks on a dead endpoint even under JAX_PLATFORMS=cpu — the same
# guard tests/conftest.py uses
from ra_tpu.utils import force_platform_from_env  # noqa: E402

force_platform_from_env()

import test_props as tp  # noqa: E402


def _disk_fault_main(argv: list) -> int:
    """--disk-faults SEED [n]: the storage-plane chaos family."""
    import test_disk_faults as tdf

    seed = int(argv[0]) if argv else 0
    n = int(argv[1]) if len(argv) > 1 else 50
    t0 = time.time()
    failed = []
    for s in range(seed, seed + n):
        with tempfile.TemporaryDirectory(prefix="soak_disk_") as d:
            try:
                tdf.run_disk_chaos(s, d)
            except Exception:  # noqa: BLE001 — report seed + continue
                failed.append(s)
                if len(failed) == 1:
                    traceback.print_exc()
    print(f"disk_faults: {n - len(failed)}/{n} ok in "
          f"{time.time() - t0:.1f}s"
          + (f"  FAILED seeds: {failed[:10]}" if failed else ""),
          flush=True)
    return 1 if failed else 0


def _superstep_main(argv: list) -> int:
    """--superstep SEED [n]: fresh fused-dispatch parity schedules."""
    import test_superstep as tss

    seed = int(argv[0]) if argv else 0
    n = int(argv[1]) if len(argv) > 1 else 50
    t0 = time.time()
    failed = []
    for s in range(seed, seed + n):
        try:
            tss.run_superstep_fuzz(s)
        except Exception:  # noqa: BLE001 — report seed + continue
            failed.append(s)
            if len(failed) == 1:
                traceback.print_exc()
    print(f"superstep: {n - len(failed)}/{n} ok in "
          f"{time.time() - t0:.1f}s"
          + (f"  FAILED seeds: {failed[:10]}" if failed else ""),
          flush=True)
    return 1 if failed else 0


def _obs_main(argv: list) -> int:
    """--obs SEED [n] [jsonl_path]: telemetry stall-detection chaos,
    Observatory snapshots streamed to a JSONL ring for ra_top."""
    import test_telemetry as tt

    seed = int(argv[0]) if argv else 0
    n = int(argv[1]) if len(argv) > 1 else 10
    path = argv[2] if len(argv) > 2 else "obs.jsonl"
    t0 = time.time()
    failed = []
    detect_windows = []
    for s in range(seed, seed + n):
        try:
            res = tt.run_stall_chaos(s, obs_path=path)
            detect_windows.append(res["detected_at"] - res["stall_from"])
        except Exception:  # noqa: BLE001 — report seed + continue
            failed.append(s)
            if len(failed) == 1:
                traceback.print_exc()
    lag = (f"  detect_lag_steps p50={sorted(detect_windows)[len(detect_windows) // 2]}"
           if detect_windows else "")
    print(f"obs_stalls: {n - len(failed)}/{n} ok in "
          f"{time.time() - t0:.1f}s{lag}  ring={path}"
          + (f"  FAILED seeds: {failed[:10]}" if failed else ""),
          flush=True)
    return 1 if failed else 0


def _blackbox_main(argv: list) -> int:
    """--blackbox SEED [n]: the flight-recorder chaos family."""
    import test_blackbox as tb

    seed = int(argv[0]) if argv else 0
    n = int(argv[1]) if len(argv) > 1 else 10
    t0 = time.time()
    failed = []
    traces = faults_seen = 0
    last = {}
    for s in range(seed, seed + n):
        with tempfile.TemporaryDirectory(prefix="soak_bb_") as d:
            try:
                last = tb.run_blackbox_chaos(s, d)
                traces += last["n_traces"]
                faults_seen += last["fault_events"]
            except Exception:  # noqa: BLE001 — report seed + continue
                failed.append(s)
                if len(failed) == 1:
                    traceback.print_exc()
    print(f"blackbox: {n - len(failed)}/{n} ok in "
          f"{time.time() - t0:.1f}s  traced_cmds={traces} "
          f"injected_faults={faults_seen}"
          + (f"  last_explained={last.get('trace')}" if last else "")
          + (f"  FAILED seeds: {failed[:10]}" if failed else ""),
          flush=True)
    return 1 if failed else 0


def _ingress_main(argv: list) -> int:
    """--ingress SEED [n] [--mesh]: the million-session fan-in soak
    (ISSUE 10).  ``--mesh`` (ISSUE 11) runs it end-to-end on lane
    state sharded across every forced-host device — 1M sessions into
    >= 100k lanes over >= 8 devices, durable with PER-DEVICE WAL
    shards, under the same disk-fault + election chaos and
    exactly-once oracle."""
    import json

    import test_ingress as ti

    mesh = "--mesh" in argv
    argv = [a for a in argv if a != "--mesh"]
    seed = int(argv[0]) if argv else 0
    n = int(argv[1]) if len(argv) > 1 else 1
    t0 = time.time()
    failed = []
    last = {}
    for s in range(seed, seed + n):
        with tempfile.TemporaryDirectory(prefix="soak_ing_") as d:
            try:
                last = ti.run_ingress_soak(
                    s, sessions=1_000_000,
                    lanes=102_400 if mesh else 10_000, waves=24,
                    wave_rows=200_000, durable_dir=d, disk_faults=True,
                    mesh=mesh)
            except Exception:  # noqa: BLE001 — report seed + continue
                failed.append(s)
                if len(failed) == 1:
                    traceback.print_exc()
    print(f"ingress{'-mesh' if mesh else ''}: "
          f"{n - len(failed)}/{n} ok in {time.time() - t0:.1f}s"
          + (f"  FAILED seeds: {failed[:10]}" if failed else ""),
          flush=True)
    if last:
        # the bench_diff-comparable tail (ingress throughput/shed keys)
        # with the host envelope (fd cap + core count, ISSUE 13 — the
        # drift dimensions the cross-host comparisons kept missing)
        from ra_tpu.wire.soak import _host_envelope
        last["host"] = _host_envelope()
        print(json.dumps(last), flush=True)
    return 1 if failed else 0


def _wire_main(argv: list) -> int:
    """--wire SEED [--durable] [--c1m]: the C10k→C1M ladder."""
    from ra_tpu.wire.soak import ladder_main

    durable = "--durable" in argv
    c1m = "--c1m" in argv
    argv = [a for a in argv if not a.startswith("--")]
    seed = int(argv[0]) if argv else 0
    rungs = [10_000, 100_000] + ([1_000_000] if c1m else [])
    t0 = time.time()
    try:
        ladder_main(seed, rungs, lanes=1024, waves=12,
                    durable=durable, disk_faults=durable)
    except Exception:  # noqa: BLE001 — report + nonzero exit
        traceback.print_exc()
        print(f"wire ladder: FAILED in {time.time() - t0:.1f}s",
              flush=True)
        return 1
    print(f"wire ladder: {len(rungs)}/{len(rungs)} rungs ok in "
          f"{time.time() - t0:.1f}s", flush=True)
    return 0


def _failover_main(argv: list) -> int:
    """--failover SEED [SEED...] [--disk-faults]: placement-failover
    soak — kill-9 one lane engine mid-traffic, classic control plane
    commits the re-placement, sessions re-home, exactly-once oracle
    over the union of both engines' state."""
    from ra_tpu.placement.soak import failover_main

    disk = "--disk-faults" in argv
    argv = [a for a in argv if not a.startswith("--")]
    seeds = [int(a) for a in argv] or [0]
    t0 = time.time()
    try:
        rows = failover_main(seeds, disk_faults=disk)
    except Exception:  # noqa: BLE001 — report + nonzero exit
        traceback.print_exc()
        print(f"failover: FAILED in {time.time() - t0:.1f}s",
              flush=True)
        return 1
    lost = sum(r["failover_lost_acked"] for r in rows)
    print(f"failover: {len(rows)}/{len(seeds)} seeds ok in "
          f"{time.time() - t0:.1f}s  lost_acked={lost}", flush=True)
    return 1 if lost else 0


def _geo_main(argv: list) -> int:
    """--geo SEED [SEED...]: the geo-distributed survival soak —
    control cluster + two engine hosts as separate OS processes behind
    a latency-domain matrix (control quorum 80-150 ms away), live TCP
    wire traffic, a delay-only episode that must migrate NOTHING, then
    SIGKILL of one engine host: detection over the reliable RPC tier,
    adoption + re-home over host_* control verbs, exactly-once oracle
    over both engines read back over RPC."""
    from ra_tpu.placement.geo import geo_main

    seeds = [int(a) for a in argv if not a.startswith("--")] or [0]
    t0 = time.time()
    try:
        rows = geo_main(seeds)
    except Exception:  # noqa: BLE001 — report + nonzero exit
        traceback.print_exc()
        print(f"geo: FAILED in {time.time() - t0:.1f}s", flush=True)
        return 1
    lost = sum(r["geo_lost_acked"] for r in rows)
    false_mig = sum(r["geo_false_migrations"] for r in rows)
    print(f"geo: {len(rows)}/{len(seeds)} seeds ok in "
          f"{time.time() - t0:.1f}s  lost_acked={lost} "
          f"false_migrations={false_mig}", flush=True)
    return 1 if (lost or false_mig) else 0


def _device_obs_main(argv: list) -> int:
    """--device-obs SEED [n]: the device-observatory chaos family."""
    import test_devicewatch as tdw

    seed = int(argv[0]) if argv else 0
    n = int(argv[1]) if len(argv) > 1 else 10
    t0 = time.time()
    failed = []
    injected = probes = 0
    for s in range(seed, seed + n):
        with tempfile.TemporaryDirectory(prefix="soak_dw_") as d:
            try:
                res = tdw.run_device_obs_chaos(s, d)
                injected += res["injected_faults"]
                probes += res["probe_recompiles"]
            except Exception:  # noqa: BLE001 — report seed + continue
                failed.append(s)
                if len(failed) == 1:
                    traceback.print_exc()
    print(f"device_obs: {n - len(failed)}/{n} ok in "
          f"{time.time() - t0:.1f}s  injected_faults={injected} "
          f"probe_recompiles_detected={probes}"
          + (f"  FAILED seeds: {failed[:10]}" if failed else ""),
          flush=True)
    return 1 if failed else 0


def _reads_main(argv: list) -> int:
    """--reads SEED [n]: the linearizable-read oracle family (ISSUE 20).

    Each seed drives every cell of {ttl_kv, stream} x {single-device,
    sharded mesh} through the read oracle — consistent reads across
    election churn, leader kills and majority partitions must reflect
    every committed write (stale serves pinned 0, refusals legal,
    healed lanes must serve) — plus one durable + disk-fault run."""
    import test_read_plane as trp

    seed = int(argv[0]) if argv else 0
    n = int(argv[1]) if len(argv) > 1 else 4
    t0 = time.time()
    failed = []
    served = refused = 0
    for s in range(seed, seed + n):
        try:
            for kind in ("ttl_kv", "stream"):
                for mesh in (False, True):
                    st = trp.run_read_oracle(s, kind, mesh=mesh,
                                             rounds=10 if mesh else 14)
                    served += st["served"]
                    refused += st["refused"]
            with tempfile.TemporaryDirectory(prefix="soak_reads_") as d:
                st = trp.run_read_oracle(s, "stream", durable_dir=d,
                                         disk_faults=True, rounds=10)
                served += st["served"]
                refused += st["refused"]
        except Exception:  # noqa: BLE001 — report seed + continue
            failed.append(s)
            if len(failed) == 1:
                traceback.print_exc()
    print(f"reads: {n - len(failed)}/{n} ok in {time.time() - t0:.1f}s  "
          f"served={served} refused={refused} stale_serves=0"
          + (f"  FAILED seeds: {failed[:10]}" if failed else ""),
          flush=True)
    return 1 if failed else 0


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "--wire":
        return _wire_main(sys.argv[2:])
    if len(sys.argv) > 1 and sys.argv[1] == "--ingress":
        return _ingress_main(sys.argv[2:])
    if len(sys.argv) > 1 and sys.argv[1] == "--blackbox":
        return _blackbox_main(sys.argv[2:])
    if len(sys.argv) > 1 and sys.argv[1] == "--disk-faults":
        return _disk_fault_main(sys.argv[2:])
    if len(sys.argv) > 1 and sys.argv[1] == "--superstep":
        return _superstep_main(sys.argv[2:])
    if len(sys.argv) > 1 and sys.argv[1] == "--obs":
        return _obs_main(sys.argv[2:])
    if len(sys.argv) > 1 and sys.argv[1] == "--device-obs":
        return _device_obs_main(sys.argv[2:])
    if len(sys.argv) > 1 and sys.argv[1] == "--failover":
        return _failover_main(sys.argv[2:])
    if len(sys.argv) > 1 and sys.argv[1] == "--geo":
        return _geo_main(sys.argv[2:])
    if len(sys.argv) > 1 and sys.argv[1] == "--reads":
        return _reads_main(sys.argv[2:])
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 400
    off = int(sys.argv[2]) if len(sys.argv) > 2 else 10_000
    families = [
        ("elections_3", lambda s: tp.test_election_safety_and_log_matching_fuzz(s, 3)),
        ("elections_5", lambda s: tp.test_election_safety_and_log_matching_fuzz(s, 5)),
        ("snapshots_3", lambda s: tp.test_safety_fuzz_with_snapshots(
            s, 3, require_snapshot=False)),
        ("membership", tp.test_safety_fuzz_with_membership_changes),
        ("member_snap", tp.test_safety_fuzz_membership_and_snapshots),
        ("mixed_macver", tp.test_safety_fuzz_mixed_machine_versions),
        ("nonassoc", tp.test_replicated_nonassoc_arithmetic_converges),
    ]
    rc = 0
    for name, fn in families:
        t0 = time.time()
        failed = []
        for seed in range(off, off + n):
            try:
                fn(seed)
            except Exception:  # noqa: BLE001 — report seed + continue family
                failed.append(seed)
                if len(failed) == 1:
                    traceback.print_exc()
        took = time.time() - t0
        print(f"{name}: {n - len(failed)}/{n} ok in {took:.1f}s"
              + (f"  FAILED seeds: {failed[:10]}" if failed else ""),
              flush=True)
        if failed:
            rc = 1
    # durable-log family needs a tmp dir per seed
    t0 = time.time()
    failed = []
    dn = max(1, n // 8)
    for seed in range(off, off + dn):
        with tempfile.TemporaryDirectory(prefix="soak_dur_") as d:
            try:
                tp.test_safety_fuzz_over_durable_logs(d, seed, 3)
            except Exception:  # noqa: BLE001
                failed.append(seed)
                if len(failed) == 1:
                    traceback.print_exc()
    print(f"durable_logs: {dn - len(failed)}/{dn} ok in "
          f"{time.time() - t0:.1f}s"
          + (f"  FAILED seeds: {failed[:10]}" if failed else ""), flush=True)
    rc = rc or (1 if failed else 0)
    # device-path chaos (engine): slower per seed (jit warms on the
    # first), so a reduced count
    import test_engine_chaos as tec
    t0 = time.time()
    failed = []
    en = max(1, n // 16)
    for seed in range(off, off + en):
        try:
            tec.run_chaos(seed, rounds=16)
        except Exception:  # noqa: BLE001
            failed.append(seed)
            if len(failed) == 1:
                traceback.print_exc()
    print(f"engine_chaos: {en - len(failed)}/{en} ok in "
          f"{time.time() - t0:.1f}s"
          + (f"  FAILED seeds: {failed[:10]}" if failed else ""), flush=True)
    return rc or (1 if failed else 0)


if __name__ == "__main__":
    sys.exit(main())
