"""Self-contained static gate — the dialyzer/xref/elvis role of the
reference's CI (/root/reference/rebar.config:30-44), implemented over
the stdlib ``ast`` because this image ships no ruff/mypy/flake8 and
installing tools is off the table.

Since ISSUE 14 the closure-gated rules are evaluated by the
whole-program engine in ``tools/analyzer/`` (AST index + CROSS-MODULE
call graph): a host sync or per-entry pickle moved into a helper one
file away no longer escapes its gate.  Since ISSUE 15 the engine also
gates the JIT PLANE (RA13 trace hazards / RA14 donation lifetime /
RA15 pytree schema, ``tools/analyzer/jitplane.py``) and evaluates the
per-file registry rules (RA05/RA06/RA07, and since ISSUE 17 the
RA16 placement retry-bound rule) as declarative FILE_RULES in
``tools/analyzer/rules.py``.  This module keeps the CLI and output
contract (``path:line: CODE msg`` + ``lint: N files, M findings``)
and the cheap generic checks (syntax/F/B/E/W + RA01/RA03); the engine
owns every other rule plus the suppression audit.

Checks (cheap, high-signal, zero-config):

  syntax        file must parse
  F401          module-level import never referenced (``__init__.py``
                re-export files and ``# noqa`` lines exempt)
  B006          mutable default argument (list/dict/set literals or
                constructors)
  E722          bare ``except:``
  F631          assert on a non-empty tuple literal (always true)
  F632          ``is``/``is not`` comparison against a str/number literal
  F541          f-string without any placeholder
  F601          duplicate constant key in a dict literal
  F811          redefinition of a function/class in the same scope
                (property setters/overloads exempt)
  W101          unreachable statement after return/raise/break/continue
  RA01          (api.py only) node-lifecycle verbs must ride the
                reliable control-plane RPC layer (transport/rpc.py):
                a direct one-shot `.send(...)`/`.remote_call(...)`
                inside a lifecycle function is the silent-loss bug
                class ISSUE 2 removed — route through node_call
  RA02          (engine lockstep.py/durable.py) no `np.asarray(...)`/
                `.item()` host syncs anywhere in the CROSS-MODULE
                transitive call closure of the step hot-loop functions
                (step/_step/submit/superstep/submit_block/...) — a
                forced device sync there serializes the XLA pipeline;
                documented readback points carry an `# ra02-ok: <why>`
                line comment
  RA03          (files in a `log/` directory only) no swallow-only
                `except OSError:`/`except Exception:` (body is just
                `pass`) around durability-bearing I/O calls — a
                silently eaten disk error is the confirmed-but-not-
                durable bug class ISSUE 4 removed; audited sites carry
                `# ra03-ok: <why>` (plus a DISK_FAULT_FIELDS counter)
  RA04          (bench.py/bench_classic.py/soak.py measured dispatch
                loops, telemetry.py sampler tick path, blackbox.py
                recorder emit path, autotune.py controller tick path,
                mesh.py drive_uniform_window) no blocking device->host
                syncs — block_until_ready/.item()/np.asarray/
                committed_total — anywhere in the cross-module closure;
                window-boundary syncs carry `# ra04-ok: <why>`.
                RA02/RA04 are one allowlist FAMILY: a line two closures
                reach carries one documented tag, either code's
  RA05          (metrics.py only) every module-level `*_FIELDS` tuple
                must be in FIELD_REGISTRY and every field documented in
                docs/OBSERVABILITY.md
  RA06          (repo source, tests exempt) every trace/flight-recorder
                event type emitted anywhere must be a key of
                blackbox.EVENT_REGISTRY, and (blackbox.py) every
                registry key documented in docs/OBSERVABILITY.md
  RA07          (autotune.py only) every TUNABLE_KNOBS knob stamped in
                the engine_pipeline overview + documented; every
                knob-mutating function emits a registered record(...)
                event — no silent knob turns
  RA08          (ingress coalesce.py offer/pop_block, mesh.py
                ingress_submit_wave) the block-build hot path stays
                vectorized across its whole cross-module closure: no
                per-session Python loops, no dict allocation;
                `# ra08-ok: <why>` allowlists (family with RA09)
  RA09          (files in a `wire/` directory) the reader sweep path:
                zero per-frame/per-command Python across the closure;
                per-CONNECTION work carries `# ra09-ok: <why>`
  RA10          (classic replication hot paths: tcp.py _send_items,
                log/durable.py write/append_batch/_put_batch,
                core/server.py _leader_aer_reply/_evaluate_quorum) no
                per-entry pickle/encode_command and no per-entry WAL
                submit/fsync inside loops, including encodes moved
                into helpers (cross-module resolved);
                `# ra10-ok: <why>` allowlists deliberate singles
  RA11          (package code, tests exempt) lock-order cycles: the
                analyzer harvests `with self._lock:` acquisitions
                (threading.Lock/RLock/Condition attributes, plus
                `# ra11-lock: Class.attr` for dynamically passed
                locks), builds the global acquisition-order graph over
                the cross-module call closure, and flags every edge on
                a cycle — the ABBA deadlock class the PR 13 review
                caught by hand (`log/durable.py` _lock vs _io_lock,
                io-then-log is the documented order; INTERNALS §15).
                `# ra11-ok: <why>` allowlists a reviewed edge
  RA12          (package code, tests exempt) thread roles: functions
                reachable from `threading.Thread(target=...)` spawn
                sites run on WORKER threads and must not touch the
                device — jax.*/jnp.*/lax.* calls, device_put,
                block_until_ready — the PR 11 mesh deadlock (an encode
                worker enqueuing multi-device work against an
                in-flight pjit), as a lint.  Host materialization
                (np.asarray of ready values, copy_to_host_async) is
                the sanctioned pattern; deliberate device ops carry
                `# ra12-ok: <why>` naming the host-materialized inputs
  RA13          (package code, tests exempt) trace hazards: inside the
                harvested TRACED closures (functions reaching jax.jit/
                pjit entry points and lax.scan/cond/while_loop bodies,
                incl. through the _build_jit-style wrapper's fn param
                and subclass overrides of resolved methods), no Python
                `if`/`while`/`assert` on tracer-typed values, no
                host-world calls (time.*/random.*/print/open, np.* on
                traced values), no `.item()`/float()/int()/bool()
                casts of traced values.  Positional params are tracers;
                keyword-only params and static_argnames are config.
                The sanctioned cond_concrete-style concreteness probe
                carries `# ra13-ok: <why>`
  RA14          (package code, tests exempt) donation lifetime: at a
                call site of a donation-enabled jitted callable
                (jax.jit(..., donate_argnums=...) — direct, or via a
                factory like _build_jit), reading the donated argument
                AFTER the call without rebinding is flagged (donated
                buffers are invalidated); and a NamedTuple pytree
                construction passing ONE buffer binding as two leaves
                (or splatting it across all leaves) is the PR 6
                "donate same buffer twice" bug as a rule.
                `# ra14-ok: <why>` allowlists
  RA15          (package code, tests exempt) pytree/sharding/checkpoint
                schema: the state schema derives from the NamedTuple
                class annotating state_shardings' state param; (a)
                every field must be covered by the shardings dispatch
                (generic `._fields` iteration or by name; stale
                by-name arms flagged), (b) the schema module's
                CHECKPOINT_FIELD_DEFAULTS registry must name every
                field (and nothing else) and restore() must consult
                it — forward-compat: an old archive restores with
                declared defaults instead of stranding a durable dir,
                (c) every staged superstep-block key
                (shardings.get("n_new")) must exist in
                superstep_block_shardings.  `# ra15-ok: <why>`
  RA16          (files in a `placement/` directory only) retry/
                escalation loops in the failover control plane: a
                While loop around process_command / consistent_query /
                reliable RPC / pacing sleep must carry deadline-or-
                bounded-attempt evidence (bound name in the loop test,
                or a bound-guarded break/raise) AND live in a function
                that emits a registered `record(...)` give-up event —
                an unbounded escalation loop against a dead peer is
                how a failover wedges forever with nothing in the
                flight recorder.  `# ra16-ok: <why>` allowlists
  AUDIT         every `raNN-ok` comment tag on a line its rule family
                no longer flags is itself an error — allowlists can't
                rot (tags inside string literals are ignored:
                suppressions are COMMENTS, tokenize decides)

Usage::

  python tools/lint.py [paths...]   # defaults to the repo source roots
  python tools/lint.py --changed    # only files differing from HEAD
  python tools/lint.py --json       # machine-readable findings
  python tools/lint.py --report     # grouped human report

Exits nonzero with one line per finding.
"""
from __future__ import annotations

import ast
import os
import subprocess
import sys
import time
from typing import Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from analyzer import (  # noqa: E402 (path bootstrap above)
    apply_suppressions, audit_suppressions, run_analysis)
from analyzer.report import render_json, render_report  # noqa: E402
from analyzer.rules import Finding  # noqa: E402

DEFAULT_TARGETS = ["ra_tpu", "tools", "tests", "bench.py",
                   "bench_classic.py", "__graft_entry__.py"]

_MUTABLE_CALLS = {"list", "dict", "set", "bytearray", "deque",
                  "defaultdict", "OrderedDict", "Counter"}


def _is_mutable_default(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        fn = node.func
        name = fn.id if isinstance(fn, ast.Name) else \
            fn.attr if isinstance(fn, ast.Attribute) else None
        return name in _MUTABLE_CALLS
    return False


def _decorator_exempts_redef(dec: ast.AST) -> bool:
    # @x.setter / @x.deleter / @overload / @singledispatchmethod.register
    if isinstance(dec, ast.Attribute):
        return True
    if isinstance(dec, ast.Name) and dec.id in ("overload",):
        return True
    if isinstance(dec, ast.Call):
        return _decorator_exempts_redef(dec.func)
    return False


_TERMINAL = (ast.Return, ast.Raise, ast.Break, ast.Continue)

#: api-layer node-LIFECYCLE verbs: cross-node start/restart/stop/delete
#: must ride the reliable RPC layer (at-most-once retries, typed
#: failures) — a raw one-shot transport call from any of these is the
#: race that loses a control-plane call to a restarting peer
_LIFECYCLE_VERBS = frozenset({
    "node_call", "start_cluster", "start_server", "restart_server",
    "stop_server", "force_delete_server",
})
_ONE_SHOT_SENDS = frozenset({"send", "remote_call"})


#: RA03 — durability-bearing I/O calls: an exception from one of these
#: inside the log layer carries a durability verdict and must never be
#: swallowed bare (fsyncgate: a confirmed write whose fsync error was
#: eaten is silent data loss)
_DURABILITY_CALLS = frozenset({"fsync", "fdatasync", "pwrite", "write",
                               "write_batch", "sync"})
_SWALLOWED_EXCS = frozenset({"OSError", "Exception", "IOError",
                             "EnvironmentError"})


def _handler_names(handler: ast.ExceptHandler) -> set:
    t = handler.type
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    return set(names)


def _check_log_io_swallow(tree: ast.Module, err) -> None:
    """RA03: in log-layer files, forbid pass-only except OSError/
    Exception handlers whose try body performs durability-bearing I/O
    (allowlist via `# ra03-ok:` on the except line)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Try):
            continue
        io_calls = set()
        for stmt in node.body:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call):
                    fn = sub.func
                    name = fn.attr if isinstance(fn, ast.Attribute) \
                        else fn.id if isinstance(fn, ast.Name) else None
                    if name in _DURABILITY_CALLS:
                        io_calls.add(name)
        if not io_calls:
            continue
        for handler in node.handlers:
            if not (_handler_names(handler) & _SWALLOWED_EXCS):
                continue
            body = handler.body
            if len(body) == 1 and isinstance(body[0], ast.Pass):
                err(handler, "RA03",
                    "swallow-only except around durability I/O "
                    f"({'/'.join(sorted(io_calls))}); route the error "
                    "through the degradation ladder or mark the line "
                    "'# ra03-ok: why' with a DISK_FAULT_FIELDS counter")


def _check_lifecycle_rpc(tree: ast.Module, err) -> None:
    """RA01: inside lifecycle verbs, forbid direct one-shot transport
    calls (they must go through the reliable RPC layer)."""
    for node in tree.body:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name not in _LIFECYCLE_VERBS:
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr in _ONE_SHOT_SENDS:
                err(sub, "RA01",
                    f"lifecycle verb {node.name}() uses one-shot "
                    f".{sub.func.attr}(); route through the reliable "
                    "RPC layer (transport/rpc.py)")


def check_file(path: str) -> list:
    """RAW per-file findings (suppressions applied by the caller)."""
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, path)
    except SyntaxError as exc:
        # the historical output contract spells this "path:N: syntax:
        # msg" — the colon rides in the code so Finding.render keeps it
        return [Finding(path, exc.lineno or 0, "syntax:",
                        str(exc.msg))]
    findings: list = []
    # format specs (the ':03d' in f"{i:03d}") are themselves JoinedStr
    # nodes with constant-only parts — never F541 candidates
    spec_ids = {id(n.format_spec) for n in ast.walk(tree)
                if isinstance(n, ast.FormattedValue)
                and n.format_spec is not None}

    def err(node: ast.AST, code: str, msg: str) -> None:
        findings.append(Finding(path, getattr(node, "lineno", 0),
                                code, msg))

    if os.path.basename(path) == "api.py":
        _check_lifecycle_rpc(tree, err)
    if os.path.basename(os.path.dirname(path)) == "log":
        _check_log_io_swallow(tree, err)
    # RA05 (field registry), RA06 (event registry) and RA07 (autotuner
    # knob contract) are evaluated by the analyzer engine's declarative
    # FILE_RULES (tools/analyzer/rules.py) since ISSUE 15 — one engine
    # owns every rule; this module keeps the cheap generic checks.

    # -- F401: unused module-level imports ------------------------------
    if os.path.basename(path) != "__init__.py":
        used = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Name):
                used.add(node.id)
            elif isinstance(node, ast.Attribute):
                pass  # base resolves through a Name anyway
        # names referenced in __all__ strings count as used
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == "__all__":
                        for elt in ast.walk(node.value):
                            if isinstance(elt, ast.Constant) and \
                                    isinstance(elt.value, str):
                                used.add(elt.value)
        for node in tree.body:
            if isinstance(node, ast.ImportFrom) and \
                    node.module == "__future__":
                continue
            if isinstance(node, ast.Import):
                for alias in node.names:
                    bound = (alias.asname or
                             alias.name.split(".")[0])
                    if bound not in used:
                        err(node, "F401",
                            f"'{alias.name}' imported but unused")
            elif isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    bound = alias.asname or alias.name
                    if bound not in used:
                        err(node, "F401",
                            f"'{alias.name}' imported but unused")

    for node in ast.walk(tree):
        # -- B006 mutable defaults --------------------------------------
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for default in list(node.args.defaults) + \
                    [d for d in node.args.kw_defaults if d is not None]:
                if _is_mutable_default(default):
                    err(default, "B006",
                        f"mutable default argument in {node.name}()")
        # -- E722 bare except -------------------------------------------
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            err(node, "E722", "bare 'except:'")
        # -- F631 assert on tuple ---------------------------------------
        if isinstance(node, ast.Assert) and \
                isinstance(node.test, ast.Tuple) and node.test.elts:
            err(node, "F631", "assert on a non-empty tuple is always true")
        # -- F632 is-literal --------------------------------------------
        if isinstance(node, ast.Compare):
            for op, comp in zip(node.ops, node.comparators):
                if isinstance(op, (ast.Is, ast.IsNot)) and \
                        isinstance(comp, ast.Constant) and \
                        isinstance(comp.value, (str, int, float, bytes)) \
                        and not isinstance(comp.value, bool):
                    err(node, "F632",
                        "'is' comparison with a literal; use ==")
        # -- F541 placeholder-less f-string -----------------------------
        if isinstance(node, ast.JoinedStr) and id(node) not in spec_ids \
                and not any(isinstance(v, ast.FormattedValue)
                            for v in node.values):
            err(node, "F541", "f-string without placeholders")
        # -- F601 duplicate dict keys -----------------------------------
        if isinstance(node, ast.Dict):
            seen: set = set()
            for key in node.keys:
                if isinstance(key, ast.Constant):
                    try:
                        if key.value in seen:
                            err(key, "F601",
                                f"duplicate dict key {key.value!r}")
                        seen.add(key.value)
                    except TypeError:
                        pass
        # -- F811 redefinition in one scope + W101 unreachable ----------
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = node.body
            defs: dict = {}
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.ClassDef)):
                    decs = getattr(stmt, "decorator_list", [])
                    if any(_decorator_exempts_redef(d) for d in decs):
                        continue
                    if stmt.name in defs:
                        err(stmt, "F811",
                            f"redefinition of '{stmt.name}' "
                            f"(first at line {defs[stmt.name]})")
                    defs[stmt.name] = stmt.lineno
        for field in ("body", "orelse", "finalbody"):
            body = getattr(node, field, None)
            if isinstance(body, list):
                for i, stmt in enumerate(body[:-1]):
                    if isinstance(stmt, _TERMINAL):
                        err(body[i + 1], "W101",
                            "unreachable code after "
                            f"{type(stmt).__name__.lower()}")
                        break
    return findings


def _collect_files(targets: list, missing: list = None) -> list:
    files: list = []
    for t in targets:
        p = os.path.join(REPO, t) if not os.path.isabs(t) else t
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".pytest_cache")]
                files += [os.path.join(root, n) for n in names
                          if n.endswith(".py")]
        elif p.endswith(".py") and os.path.exists(p):
            files.append(p)
        elif missing is not None:
            # a typo'd/nonexistent explicit target must fail LOUDLY —
            # a gate that silently lints nothing reports green on a
            # misconfiguration (review finding)
            missing.append(t)
    return sorted(set(files))


def _default_source_files() -> list:
    """The repo's source roots minus tests — what single-file
    invocations index so cross-module edges resolve the same way the
    full run resolves them."""
    return _collect_files(["ra_tpu", "tools", "bench.py",
                           "bench_classic.py", "__graft_entry__.py"])


def _changed_targets() -> Optional[list]:
    """Files differing from HEAD (staged, unstaged, untracked) — the
    fast local loop (`tools/lint.py --changed`).  Returns None when
    git itself fails: silently widening to the full default target set
    would hand the user findings for files they never touched (the
    same silent-misconfiguration class as a typo'd target)."""
    names: set = set()
    for cmd in (["git", "-C", REPO, "diff", "--name-only", "HEAD"],
                ["git", "-C", REPO, "ls-files", "--others",
                 "--exclude-standard"]):
        try:
            out = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if out.returncode != 0:
            return None
        names.update(x.strip() for x in out.stdout.splitlines()
                     if x.strip())
    return sorted(n for n in names if n.endswith(".py")
                  and os.path.exists(os.path.join(REPO, n)))


def main(argv: list) -> int:
    flags = {a for a in argv if a.startswith("--")}
    paths = [a for a in argv if not a.startswith("--")]
    unknown = flags - {"--json", "--report", "--changed"}
    if unknown:
        print(f"lint: unknown flags {sorted(unknown)}", file=sys.stderr)
        return 2
    if "--changed" in flags:
        if paths:
            # explicit paths would be silently discarded — a user
            # scoping the fast loop to a subtree must not get results
            # for unrelated files with no warning
            print("lint: --changed takes no explicit targets",
                  file=sys.stderr)
            return 2
        targets = _changed_targets()
        if targets is None:
            print("lint: --changed could not read the git diff; "
                  "run without --changed for a full pass",
                  file=sys.stderr)
            return 2
        if not targets:
            print("lint: 0 files, 0 findings")
            return 0
    else:
        targets = paths or DEFAULT_TARGETS
    t0 = time.monotonic()
    missing: list = []
    files = _collect_files(targets, missing)
    if missing:
        for m in missing:
            print(f"lint: no such target: {m}", file=sys.stderr)
        return 2
    raw: list = []
    for f in files:
        raw += check_file(f)
    engine_raw, _idx = run_analysis(
        files, repo=REPO, default_sources=_default_source_files())
    seen = {x.key() for x in raw}
    engine_raw = [x for x in engine_raw if x.key() not in seen]
    # the engine evaluates the WHOLE indexed program so a scoped run
    # (--changed, one file) produces the same raw pool as the full run
    # — that pool feeds the audit, or a tag in a changed helper would
    # read as stale whenever its closure ROOT didn't change (review
    # finding).  REPORT only findings attributable to the targets: the
    # finding's own file, or a rule root that reaches it (so the
    # cross-module escape rooted in a linted file still surfaces
    # wherever the construct lives, but linting fixture A never
    # reports sibling B's independent findings).
    target_set = set(files)
    raw_full = raw + engine_raw
    raw += [x for x in engine_raw
            if x.path in target_set
            or any(r in target_set for r in x.roots)]
    active, suppressed = apply_suppressions(raw)
    active += audit_suppressions(files, raw_full)
    active.sort(key=lambda f: (f.path, f.line, f.code))
    elapsed = time.monotonic() - t0
    if "--json" in flags:
        print(render_json(files, active, suppressed, elapsed))
    elif "--report" in flags:
        print(render_report(files, active, suppressed, elapsed,
                            repo=REPO))
    else:
        for f in active:
            print(f.render())
        print(f"lint: {len(files)} files, {len(active)} findings")
    return 1 if active else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
