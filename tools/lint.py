"""Self-contained static gate — the dialyzer/xref/elvis role of the
reference's CI (/root/reference/rebar.config:30-44), implemented over
the stdlib ``ast`` because this image ships no ruff/mypy/flake8 and
installing tools is off the table.

Checks (cheap, high-signal, zero-config):

  syntax        file must parse
  F401          module-level import never referenced (``__init__.py``
                re-export files and ``# noqa`` lines exempt)
  B006          mutable default argument (list/dict/set literals or
                constructors)
  E722          bare ``except:``
  F631          assert on a non-empty tuple literal (always true)
  F632          ``is``/``is not`` comparison against a str/number literal
  F541          f-string without any placeholder
  F601          duplicate constant key in a dict literal
  F811          redefinition of a function/class in the same scope
                (property setters/overloads exempt)
  W101          unreachable statement after return/raise/break/continue
  RA01          (api.py only) node-lifecycle verbs must ride the
                reliable control-plane RPC layer (transport/rpc.py):
                a direct one-shot `.send(...)`/`.remote_call(...)`
                inside a lifecycle function is the silent-loss bug
                class ISSUE 2 removed — route through node_call
  RA02          (engine lockstep.py/durable.py only) no
                `np.asarray(...)`/`.item()` host syncs inside the step
                hot-loop functions (step/_step/submit/uniform_step) —
                a forced device sync there serializes the XLA
                pipeline; documented readback points carry an
                `# ra02-ok: <why>` line comment
  RA04          (bench.py/bench_classic.py/soak.py only) no host
                syncs inside the measured region of a bench/soak
                dispatch loop: a loop that dispatches engine work
                (`.step(...)`/`.superstep(...)`/`.uniform_*`/a
                driver `.submit(...)`) must not call
                `block_until_ready`/`.item()`/`np.asarray(...)`/
                `committed_total()` — each forces a device->host sync
                that serializes the pipeline the measurement claims
                to measure; window-boundary syncs carry an
                `# ra04-ok: <why>` line comment.  ALSO gates the
                telemetry sampler path (telemetry.py tick/
                _start_sample/_harvest): the sampler rides the
                dispatch loop, so its tick path obeys the same
                no-blocking-sync contract; and the MESH driver's
                dispatch loop (mesh.py drive_uniform_window + its
                same-module call closure, ISSUE 11) — the sharded
                frontier's measured loop obeys the same contract
  RA05          (metrics.py only) every module-level counter-field
                tuple (`*_FIELDS`) must be listed in FIELD_REGISTRY
                (the registry parity test iterates it) and every field
                name documented in docs/OBSERVABILITY.md — a field the
                registry or the doc does not know is a metric nobody
                can interpret (the drop-silently bug class ISSUE 6's
                telemetry_dropped self-metric removed, applied to the
                registry itself)
  RA06          (repo source, tests exempt) every trace/flight-recorder
                event type emitted anywhere — ``record("...")`` /
                ``blackbox.record`` / ``RECORDER.record`` / module-level
                ``trace.span("...")`` / ``trace.instant("...")`` — must
                be a key of the central ``EVENT_REGISTRY``
                (ra_tpu/blackbox.py), and, when linting blackbox.py
                itself, every registry key must be documented
                (backticked) in docs/OBSERVABILITY.md — the RA05
                field-registry parity applied to events.  The RA04
                no-host-sync gate also covers the recorder's emit path
                (blackbox.py ``record`` closure): the recorder rides
                dispatch loops and WAL threads, so a blocking sync
                there is the same bug class as a sampler-tick sync
  RA07          (autotune.py only) the closed-loop controller
                contract (ISSUE 9): every knob in TUNABLE_KNOBS must
                be stamped in the engine_pipeline overview
                (telemetry.py engine source) and documented in
                docs/OBSERVABILITY.md, and every function that
                mutates a knob must emit a registered EVENT_REGISTRY
                event via record(...) — no silent knob turns; the
                tuner's tick path also rides the RA04 no-host-sync
                closure gate (it runs between dispatches)
  RA08          (ingress coalesce.py only) the block-build hot path
                (`offer`/`pop_block` + every same-module helper they
                reach) must stay vectorized: no per-session Python
                loops (for/while/comprehensions) and no dict
                allocation (literals, comprehensions, dict() calls) —
                a per-row Python loop there turns the million-session
                fan-in back into per-command host work, the cost class
                the coalescer exists to remove; a deliberate exception
                carries an `# ra08-ok: <why>` line comment.  The
                INGRESS_FIELDS registry/doc half rides RA05 (the tuple
                lives in metrics.py like every other group).  ALSO
                gates the mesh-side ingress pump path (mesh.py
                ingress_submit_wave + closure, ISSUE 11): per-session
                Python on the sharded fan-in is the same cost class
  RA09          (files in a `wire/` directory only, ISSUE 12) the
                wire reader SWEEP path (`sweep` + every same-module
                helper it reaches) must do zero per-frame/per-command
                Python work: no Python loops (for/while/
                comprehensions) and no dict allocation — the sweep
                runs for every ingress pass at up-to-millions-of-
                frames rates, and a per-frame Python object there
                reintroduces exactly the per-command cost the
                preallocated-ring design removes (RA08 extended to
                the socket path).  Per-CONNECTION work (a socket
                write per conn, a protocol-error close) carries an
                `# ra09-ok: <why>` line comment
  RA10          (classic replication hot path, ISSUE 13) no per-entry
                `pickle.dumps`/`encode_command` and no per-entry WAL
                append/fsync INSIDE A LOOP within the batch-native hot
                paths: the transport sender loop (`tcp.py::_send_items`
                + same-module closure), the follower/leader batch
                append (`log/durable.py::write`/`append_batch`/
                `_put_batch` + closure), and the leader commit-advance
                closure (`core/server.py::_leader_aer_reply`/
                `_evaluate_quorum`).  Calls to same-module helpers that
                themselves encode (contain a dumps/encode_command) are
                flagged at the loop call site too — moving the pickle
                into a helper must not escape the gate.  Deliberate
                per-item sites (control-plane singles, the
                no-shipped-payloads fallback, crash-recovery resends)
                carry an `# ra10-ok: <why>` line comment
  RA03          (files in a `log/` directory only) no swallow-only
                `except OSError:`/`except Exception:` (body is just
                `pass`) around durability-bearing I/O calls (fsync/
                fdatasync/pwrite/write/write_batch/sync) — a silently
                eaten disk error there is the confirmed-but-not-durable
                bug class ISSUE 4 removed; each site must either feed
                the DiskFaultPlan degradation ladder or carry an
                `# ra03-ok: <why>` comment (plus a
                DISK_FAULT_FIELDS counter)

Usage: ``python tools/lint.py [paths...]`` (defaults to the repo's
source roots).  Exits nonzero with one line per finding.
"""
from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DEFAULT_TARGETS = ["ra_tpu", "tools", "tests", "bench.py",
                   "bench_classic.py", "__graft_entry__.py"]

_MUTABLE_CALLS = {"list", "dict", "set", "bytearray", "deque",
                  "defaultdict", "OrderedDict", "Counter"}


def _is_mutable_default(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        fn = node.func
        name = fn.id if isinstance(fn, ast.Name) else \
            fn.attr if isinstance(fn, ast.Attribute) else None
        return name in _MUTABLE_CALLS
    return False


def _decorator_exempts_redef(dec: ast.AST) -> bool:
    # @x.setter / @x.deleter / @overload / @singledispatchmethod.register
    if isinstance(dec, ast.Attribute):
        return True
    if isinstance(dec, ast.Name) and dec.id in ("overload",):
        return True
    if isinstance(dec, ast.Call):
        return _decorator_exempts_redef(dec.func)
    return False


_TERMINAL = (ast.Return, ast.Raise, ast.Break, ast.Continue)

#: api-layer node-LIFECYCLE verbs: cross-node start/restart/stop/delete
#: must ride the reliable RPC layer (at-most-once retries, typed
#: failures) — a raw one-shot transport call from any of these is the
#: race that loses a control-plane call to a restarting peer
_LIFECYCLE_VERBS = frozenset({
    "node_call", "start_cluster", "start_server", "restart_server",
    "stop_server", "force_delete_server",
})
_ONE_SHOT_SENDS = frozenset({"send", "remote_call"})


#: RA02 — engine step hot loop (files named lockstep.py/durable.py):
#: functions on the per-step dispatch path must never force a device->
#: host sync.  `np.asarray(...)` or `.item()` on a device array there
#: serializes the XLA pipeline (a ~35-70ms stall per step on tunneled
#: backends) — the bug class the round-5 profile work removed.  The
#: documented readback points (the durability bridge's encode workers,
#: overview/readback helpers) run off-thread or out of the loop; a
#: deliberate host-side conversion inside the loop carries an
#: `# ra02-ok: <why>` comment on its line.
_HOT_STEP_FUNCS = frozenset({"step", "_step", "submit", "uniform_step",
                             "superstep", "_superstep", "submit_block",
                             "uniform_superstep"})
_ENGINE_HOT_FILES = frozenset({"lockstep.py", "durable.py"})


def _check_engine_hot_sync(tree: ast.Module, err) -> None:
    """RA02: forbid np.asarray/.item() host syncs inside the engine
    step hot-loop functions (allowlist via `# ra02-ok:` line comment)."""
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name not in _HOT_STEP_FUNCS:
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            fn = sub.func
            if not isinstance(fn, ast.Attribute):
                continue
            if fn.attr == "asarray" and \
                    isinstance(fn.value, ast.Name) and \
                    fn.value.id == "np":
                err(sub, "RA02",
                    f"np.asarray() in hot-loop {node.name}() forces a "
                    "device->host sync; move it to a documented "
                    "readback point or mark the line '# ra02-ok: why'")
            elif fn.attr == "item" and not sub.args:
                err(sub, "RA02",
                    f".item() in hot-loop {node.name}() forces a "
                    "device->host sync; move it to a documented "
                    "readback point or mark the line '# ra02-ok: why'")


#: RA04 — bench/soak measured loops (files named bench.py/
#: bench_classic.py/soak.py): a loop that dispatches engine work must
#: never force a device->host sync between dispatches — a
#: block_until_ready/.item()/np.asarray/committed_total there
#: serializes the XLA pipeline and the "measured" number quietly
#: becomes a dispatch-latency benchmark (the regression class the
#: ISSUE 5 dispatch-ahead work removed).  Window-boundary syncs (the
#: in-flight cap, a sample boundary, a solo-step probe) carry an
#: `# ra04-ok: <why>` comment on their line.
_BENCH_FILES = frozenset({"bench.py", "bench_classic.py", "soak.py"})
_DISPATCH_ATTRS = frozenset({"step", "superstep", "uniform_step",
                             "uniform_superstep", "submit"})
_SYNC_ATTRS = frozenset({"block_until_ready", "committed_total", "item"})


def _check_bench_loop_sync(tree: ast.Module, err) -> None:
    """RA04: forbid host syncs inside bench/soak dispatch loops
    (allowlist via `# ra04-ok:` line comment)."""
    seen: set = set()  # dedup: nested loops walk the same call twice
    for node in ast.walk(tree):
        if not isinstance(node, (ast.While, ast.For, ast.AsyncFor)):
            continue
        body = list(node.body) + list(node.orelse)
        calls = [sub for stmt in body for sub in ast.walk(stmt)
                 if isinstance(sub, ast.Call)
                 and isinstance(sub.func, ast.Attribute)]
        if not any(c.func.attr in _DISPATCH_ATTRS for c in calls):
            continue
        for c in calls:
            if id(c) in seen:
                continue
            seen.add(id(c))
            attr = c.func.attr
            if attr in ("item", "committed_total") and c.args:
                continue  # item(k)/... with args is not the sync form
            if attr in _SYNC_ATTRS:
                err(c, "RA04",
                    f".{attr}() inside a bench dispatch loop forces a "
                    "device->host sync that serializes the measured "
                    "pipeline; harvest async readbacks instead or mark "
                    "the line '# ra04-ok: why' (window boundary)")
            elif attr == "asarray" and \
                    isinstance(c.func.value, ast.Name) and \
                    c.func.value.id == "np":
                err(c, "RA04",
                    "np.asarray() inside a bench dispatch loop forces "
                    "a device->host sync that serializes the measured "
                    "pipeline; harvest async readbacks instead or mark "
                    "the line '# ra04-ok: why' (window boundary)")


#: RA04 (sampler extension) — the telemetry sampler's dispatch-loop
#: path (telemetry.py): ``tick`` is called by the engine after every
#: dispatch, so it and the helpers it drives must start async work
#: only — a block_until_ready/.item()/np.asarray there would hand the
#: "zero new host syncs" guarantee back.  Out-of-loop conversions
#: (a ready-gated harvest, the explicit ``drain`` barrier) carry an
#: `# ra04-ok: <why>` line comment.
_TELEMETRY_FILES = frozenset({"telemetry.py"})
#: ``note`` is the phase-stamp entry point (PhaseStats): it rides the
#: dispatch thread, the WAL batch threads and the encode workers, so
#: the no-host-sync closure gate covers it too (ISSUE 9)
_SAMPLER_HOT_FUNCS = frozenset({"tick", "_start_sample", "_harvest",
                                "note"})
#: the flight recorder's emit path rides the same dispatch loops the
#: sampler tick does — same no-host-sync contract (RA04 extension,
#: ISSUE 7)
_BLACKBOX_FILES = frozenset({"blackbox.py"})
_RECORDER_HOT_FUNCS = frozenset({"record"})

#: RA07 — the autotuner contract (files named autotune.py, ISSUE 9):
#: (a) every knob in the module's TUNABLE_KNOBS tuple must be stamped
#: in the engine_pipeline overview (the telemetry.py engine source —
#: a knob the overview does not carry turns invisibly: the ring shows
#: its effects with no record of its value) and documented (backticked)
#: in docs/OBSERVABILITY.md; (b) every function that MUTATES a knob
#: (an assignment into ``knobs[...]`` or to an attribute named after a
#: knob) must emit a registered EVENT_REGISTRY event via record(...) in
#: the same function — no silent knob turns.  The controller tick path
#: additionally rides the RA04 no-host-sync closure gate: the tuner
#: runs between dispatches, so a blocking sync there stalls the very
#: pipeline it tunes.
_AUTOTUNE_FILES = frozenset({"autotune.py"})
_TUNER_HOT_FUNCS = frozenset({"tick"})


def _tunable_knobs(tree: ast.Module) -> list:
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "TUNABLE_KNOBS" and \
                isinstance(node.value, ast.Tuple):
            return [(node, e.value) for e in node.value.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)]
    return []


def _check_autotune_contract(tree: ast.Module, err, path: str,
                             doc_text, keys) -> None:
    """RA07 (see the block comment above)."""
    knobs = _tunable_knobs(tree)
    knob_names = {k for _n, k in knobs}
    # (a) knob stamping: the engine_pipeline overview lives in
    # telemetry.py (the Observatory engine source) — prefer one next to
    # the checked file (self-contained fixtures), else the repo's
    tel = os.path.join(os.path.dirname(path), "telemetry.py")
    if not os.path.exists(tel):
        tel = os.path.join(REPO, "ra_tpu", "telemetry.py")
    tel_text = None
    if os.path.exists(tel):
        with open(tel, encoding="utf-8") as f:
            tel_text = f.read()
    for node, knob in knobs:
        if tel_text is not None and f'"{knob}"' not in tel_text \
                and f"'{knob}'" not in tel_text:
            err(node, "RA07",
                f"tunable knob {knob!r} is not stamped in the "
                "engine_pipeline overview (telemetry.py engine "
                "source); a knob the overview does not carry turns "
                "invisibly")
        if doc_text is not None and f"`{knob}`" not in doc_text:
            err(node, "RA07",
                f"tunable knob {knob!r} undocumented in "
                "docs/OBSERVABILITY.md")
    # (b) no silent knob turns: a knob-mutating function must record a
    # registered event
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        mutates = None
        for sub in ast.walk(node):
            targets = []
            if isinstance(sub, ast.Assign):
                targets = sub.targets
            elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                targets = [sub.target]
            for t in targets:
                if isinstance(t, ast.Subscript):
                    base = t.value
                    name = base.attr if isinstance(base, ast.Attribute) \
                        else base.id if isinstance(base, ast.Name) else None
                    if name == "knobs":
                        mutates = sub
                elif isinstance(t, ast.Attribute) and \
                        t.attr in knob_names:
                    mutates = sub
        if mutates is None:
            continue
        recorded = False
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and sub.args and \
                    isinstance(sub.args[0], ast.Constant) and \
                    isinstance(sub.args[0].value, str):
                fn = sub.func
                name = fn.id if isinstance(fn, ast.Name) else \
                    fn.attr if isinstance(fn, ast.Attribute) else None
                if name == "record" and \
                        (keys is None or sub.args[0].value in keys):
                    recorded = True
        if not recorded:
            err(mutates, "RA07",
                f"{node.name}() mutates an autotuner knob without "
                "emitting a registered record(...) event — silent "
                "knob turns are unreconstructable (register the "
                "decision in EVENT_REGISTRY)")


def _sampler_hot_closure(tree: ast.Module,
                         roots=_SAMPLER_HOT_FUNCS) -> dict:
    """Module functions reachable from the given entry points via
    same-module calls (``name(...)`` or ``self.name(...)``) — a host
    sync moved into a helper must not escape the gate."""
    funcs: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            funcs.setdefault(node.name, node)
    hot: dict = {}
    queue = [n for n in roots if n in funcs]
    while queue:
        name = queue.pop()
        if name in hot:
            continue
        hot[name] = funcs[name]
        for sub in ast.walk(funcs[name]):
            if not isinstance(sub, ast.Call):
                continue
            fn = sub.func
            callee = None
            if isinstance(fn, ast.Name):
                callee = fn.id
            elif isinstance(fn, ast.Attribute) and \
                    isinstance(fn.value, ast.Name) and fn.value.id == "self":
                callee = fn.attr
            if callee in funcs:
                queue.append(callee)
    return hot


def _check_sampler_sync(tree: ast.Module, err,
                        roots=_SAMPLER_HOT_FUNCS) -> None:
    """RA04 on the telemetry sampler path: forbid host syncs in the
    tick-path functions AND every same-module helper they reach
    (allowlist via `# ra04-ok:` line comment)."""
    for node in _sampler_hot_closure(tree, roots).values():
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            fn = sub.func
            if not isinstance(fn, ast.Attribute):
                continue
            if fn.attr in _SYNC_ATTRS and not sub.args:
                err(sub, "RA04",
                    f".{fn.attr}() in sampler tick-path {node.name}() "
                    "blocks the dispatch loop the sampler rides; gate "
                    "on is_ready() or mark the line '# ra04-ok: why'")
            elif fn.attr == "asarray" and \
                    isinstance(fn.value, ast.Name) and fn.value.id == "np":
                err(sub, "RA04",
                    f"np.asarray() in sampler tick-path {node.name}() "
                    "blocks the dispatch loop the sampler rides; gate "
                    "on is_ready() or mark the line '# ra04-ok: why'")


#: RA08 — the ingress coalescer's block-build hot path (files named
#: coalesce.py, ISSUE 10): offer/pop_block run for every ingress wave
#: at up-to-millions-of-rows rates, so they and every same-module
#: helper they reach must stay vectorized — a per-session Python loop
#: or a per-row dict allocation there reintroduces exactly the
#: per-command host work the dense-block design removes.
_INGRESS_HOT_FILES = frozenset({"coalesce.py"})
_COALESCE_HOT_FUNCS = frozenset({"offer", "pop_block"})
_LOOP_NODES = (ast.For, ast.AsyncFor, ast.While, ast.ListComp,
               ast.SetComp, ast.DictComp, ast.GeneratorExp)

#: RA04/RA08 (mesh extension, ISSUE 11) — the mesh driver module
#: (files named mesh.py): ``drive_uniform_window`` is the sharded
#: frontier's measured dispatch loop, so its same-module call closure
#: rides the RA04 no-host-sync gate exactly like the bench loops; the
#: mesh-side ingress pump path (``ingress_submit_wave`` + closure)
#: rides RA08's no-per-session-Python gate — a per-session loop there
#: would put per-command host work back on the 100k-lane fan-in.
_MESH_FILES = frozenset({"mesh.py"})
_MESH_DISPATCH_FUNCS = frozenset({"drive_uniform_window"})
_MESH_INGRESS_FUNCS = frozenset({"ingress_submit_wave"})

#: RA09 — the wire reader sweep path (files in a `wire/` directory,
#: ISSUE 12): `sweep` + its same-module call closure is the zero-per-
#: command contract the whole wire plane is built on — length-prefixed
#: frames land in preallocated rings and are decoded by ONE vectorized
#: pass, so a per-frame Python loop or allocation there is the RA08
#: bug class extended to the socket path.  Per-CONNECTION work (one
#: socket write per conn, a protocol-error close) is allowlisted via
#: `# ra09-ok: <why>` line comments.
_WIRE_SWEEP_FUNCS = frozenset({"sweep"})


def _check_coalesce_hot_path(tree: ast.Module, err,
                             roots=_COALESCE_HOT_FUNCS,
                             code: str = "RA08",
                             what: str = "coalescer") -> None:
    """RA08/RA09: forbid Python loops and dict allocation in a
    vectorized hot path (allowlist via `# ra08-ok:`/`# ra09-ok:` line
    comment — resolved by the caller's err wrapper)."""
    mark = f"# {code.lower()}-ok: why"
    for node in _sampler_hot_closure(tree, roots).values():
        for sub in ast.walk(node):
            if isinstance(sub, _LOOP_NODES):
                err(sub, code,
                    f"Python loop in {what} hot path {node.name}() "
                    "— per-row iteration turns the vectorized "
                    "path back into per-command host work; "
                    "vectorize (argsort/fancy indexing) or mark the "
                    f"line '{mark}'")
            elif isinstance(sub, ast.Dict):
                err(sub, code,
                    f"dict allocation in {what} hot path "
                    f"{node.name}(); preallocate outside the hot path "
                    f"or mark the line '{mark}'")
            elif isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Name) and \
                    sub.func.id == "dict":
                err(sub, code,
                    f"dict() allocation in {what} hot path "
                    f"{node.name}(); preallocate outside the hot path "
                    f"or mark the line '{mark}'")


#: RA10 — the classic replication hot path (ISSUE 13): per scoped file,
#: the root functions whose same-module call closure must not pickle or
#: touch the WAL per entry inside a loop.  Scope key: (basename,
#: required parent dir or None).
_RA10_SCOPES = {
    ("tcp.py", None): frozenset({"_send_items"}),
    ("durable.py", "log"): frozenset({"write", "append_batch",
                                      "_put_batch"}),
    ("server.py", "core"): frozenset({"_leader_aer_reply",
                                      "_evaluate_quorum"}),
}
_RA10_ENCODE_NAMES = frozenset({"dumps", "encode_command"})
_RA10_SYNC_NAMES = frozenset({"fsync", "fdatasync"})


def _check_classic_hot_path(tree: ast.Module, err, roots) -> None:
    """RA10: inside the hot-path closure, flag per-entry encode/WAL
    calls that sit INSIDE a loop (allowlist via `# ra10-ok:` line
    comment, resolved by the caller's err wrapper)."""
    funcs: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            funcs.setdefault(node.name, node)
    # same-module helpers that themselves encode: calling one inside a
    # loop is the same per-entry pickle, one hop removed
    encoders = set()
    for name, fn in funcs.items():
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Call):
                f = sub.func
                cname = f.attr if isinstance(f, ast.Attribute) else \
                    f.id if isinstance(f, ast.Name) else None
                if cname in _RA10_ENCODE_NAMES:
                    encoders.add(name)
                    break
    seen: set = set()
    for node in _sampler_hot_closure(tree, roots).values():
        for loop in ast.walk(node):
            if not isinstance(loop, _LOOP_NODES):
                continue
            for sub in ast.walk(loop):
                if not isinstance(sub, ast.Call) or id(sub) in seen:
                    continue
                f = sub.func
                cname = f.attr if isinstance(f, ast.Attribute) else \
                    f.id if isinstance(f, ast.Name) else None
                if cname in _RA10_SYNC_NAMES or (
                        cname in ("write", "write_many") and
                        isinstance(f, ast.Attribute) and
                        isinstance(f.value, ast.Attribute) and
                        f.value.attr == "wal"):
                    seen.add(id(sub))
                    err(sub, "RA10",
                        f"per-entry WAL submit/sync ({cname}) inside a "
                        f"loop in classic hot path {node.name}() — use "
                        "the group-commit fan-in (write_many) outside "
                        "the loop or mark the line '# ra10-ok: why'")
                elif cname in _RA10_ENCODE_NAMES or cname in encoders:
                    seen.add(id(sub))
                    err(sub, "RA10",
                        f"per-entry encode ({cname}) inside a loop in "
                        f"classic hot path {node.name}() — batch-encode "
                        "outside the loop (one pickle per frame/run) or "
                        "mark the line '# ra10-ok: why'")


#: RA05 — the field-group registry contract (metrics.py): a counter
#: field that FIELD_REGISTRY does not list escapes the registry parity
#: test, and one docs/OBSERVABILITY.md does not name is a number nobody
#: can interpret — both are flagged at the definition site.
def _check_field_registry(tree: ast.Module, err, doc_text) -> None:
    groups: dict = {}
    registry_names: set = set()
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        name = node.targets[0].id
        if name.endswith("_FIELDS") and isinstance(node.value, ast.Tuple):
            fields = [e.value for e in node.value.elts
                      if isinstance(e, ast.Constant)
                      and isinstance(e.value, str)]
            groups[name] = (node, fields)
        elif name == "FIELD_REGISTRY" and isinstance(node.value, ast.Dict):
            for v in node.value.values:
                if isinstance(v, ast.Name):
                    registry_names.add(v.id)
    for name, (node, fields) in groups.items():
        if name not in registry_names:
            err(node, "RA05",
                f"counter-field tuple {name} is not listed in "
                "FIELD_REGISTRY; the registry parity test cannot "
                "cover it")
        if doc_text is not None:
            missing = [f for f in fields if f"`{f}`" not in doc_text]
            if missing:
                err(node, "RA05",
                    f"{name} fields undocumented in "
                    f"docs/OBSERVABILITY.md: {missing[:6]}")


#: RA03 — durability-bearing I/O calls: an exception from one of these
#: inside the log layer carries a durability verdict and must never be
#: swallowed bare (fsyncgate: a confirmed write whose fsync error was
#: eaten is silent data loss)
_DURABILITY_CALLS = frozenset({"fsync", "fdatasync", "pwrite", "write",
                               "write_batch", "sync"})
_SWALLOWED_EXCS = frozenset({"OSError", "Exception", "IOError",
                             "EnvironmentError"})


def _handler_names(handler: ast.ExceptHandler) -> set:
    t = handler.type
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    return set(names)


def _check_log_io_swallow(tree: ast.Module, err) -> None:
    """RA03: in log-layer files, forbid pass-only except OSError/
    Exception handlers whose try body performs durability-bearing I/O
    (allowlist via `# ra03-ok:` on the except line)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Try):
            continue
        io_calls = set()
        for stmt in node.body:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call):
                    fn = sub.func
                    name = fn.attr if isinstance(fn, ast.Attribute) \
                        else fn.id if isinstance(fn, ast.Name) else None
                    if name in _DURABILITY_CALLS:
                        io_calls.add(name)
        if not io_calls:
            continue
        for handler in node.handlers:
            if not (_handler_names(handler) & _SWALLOWED_EXCS):
                continue
            body = handler.body
            if len(body) == 1 and isinstance(body[0], ast.Pass):
                err(handler, "RA03",
                    "swallow-only except around durability I/O "
                    f"({'/'.join(sorted(io_calls))}); route the error "
                    "through the degradation ladder or mark the line "
                    "'# ra03-ok: why' with a DISK_FAULT_FIELDS counter")


#: RA06 — the event-type registry contract (ISSUE 7): an event type
#: the registry does not know cannot be interpreted by ra_trace, the
#: ra_top incident footer, or the docs — flagged at the emit site.
#: Tests are exempt (fixtures emit throwaway span names); the real
#: instrumentation lives in ra_tpu/ and tools/.

def _event_registry_keys(path: str):
    """Keys of blackbox.EVENT_REGISTRY: prefer a ``blackbox.py`` next
    to the checked file (self-contained fixtures), else the repo's."""
    cand = os.path.join(os.path.dirname(path), "blackbox.py")
    if not os.path.exists(cand):
        cand = os.path.join(REPO, "ra_tpu", "blackbox.py")
    if not os.path.exists(cand):
        return None
    try:
        with open(cand, encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return None
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "EVENT_REGISTRY" and \
                isinstance(node.value, ast.Dict):
            return {k.value for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
    return None


def _check_event_registry_use(tree: ast.Module, err, keys: set) -> None:
    """RA06: every string-constant event type passed to the recorder
    (``record(...)``, ``blackbox.record``, ``RECORDER.record``) or to a
    module-level tracer site (``trace.span``/``trace.instant``) must be
    a registry key.  Tracer OBJECT spans (``t.span``) are exempt — user
    code may span whatever it likes; the registry governs the repo's
    own instrumentation vocabulary."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        fn = node.func
        via = None
        if isinstance(fn, ast.Name) and fn.id == "record":
            via = "record"
        elif isinstance(fn, ast.Attribute) and fn.attr == "record" and \
                isinstance(fn.value, ast.Name) and \
                fn.value.id in ("blackbox", "RECORDER"):
            via = f"{fn.value.id}.record"
        elif isinstance(fn, ast.Attribute) and \
                fn.attr in ("span", "instant") and \
                isinstance(fn.value, ast.Name) and fn.value.id == "trace":
            via = f"trace.{fn.attr}"
        if via is None:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
                and arg.value not in keys:
            err(node, "RA06",
                f"event type {arg.value!r} emitted via {via}() is not "
                "in blackbox.EVENT_REGISTRY; register and document it "
                "(docs/OBSERVABILITY.md) or ra_trace/ra_top cannot "
                "interpret it")


def _check_event_registry_doc(tree: ast.Module, err, doc_text) -> None:
    """RA06 (doc half, blackbox.py only): every EVENT_REGISTRY key must
    be named (backticked) in docs/OBSERVABILITY.md."""
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "EVENT_REGISTRY" and \
                isinstance(node.value, ast.Dict):
            keys = [k.value for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)]
            if doc_text is not None:
                missing = [k for k in keys if f"`{k}`" not in doc_text]
                if missing:
                    err(node, "RA06",
                        "EVENT_REGISTRY keys undocumented in "
                        f"docs/OBSERVABILITY.md: {missing[:6]}")


def _check_lifecycle_rpc(tree: ast.Module, err) -> None:
    """RA01: inside lifecycle verbs, forbid direct one-shot transport
    calls (they must go through the reliable RPC layer)."""
    for node in tree.body:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name not in _LIFECYCLE_VERBS:
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr in _ONE_SHOT_SENDS:
                err(sub, "RA01",
                    f"lifecycle verb {node.name}() uses one-shot "
                    f".{sub.func.attr}(); route through the reliable "
                    "RPC layer (transport/rpc.py)")


def check_file(path: str) -> list:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, path)
    except SyntaxError as exc:
        return [f"{path}:{exc.lineno}: syntax: {exc.msg}"]
    errors: list = []
    noqa = {i + 1 for i, line in enumerate(src.splitlines())
            if "noqa" in line}
    # format specs (the ':03d' in f"{i:03d}") are themselves JoinedStr
    # nodes with constant-only parts — never F541 candidates
    spec_ids = {id(n.format_spec) for n in ast.walk(tree)
                if isinstance(n, ast.FormattedValue)
                and n.format_spec is not None}

    def err(node: ast.AST, code: str, msg: str) -> None:
        line = getattr(node, "lineno", 0)
        if line not in noqa:
            errors.append(f"{path}:{line}: {code} {msg}")

    if os.path.basename(path) == "api.py":
        _check_lifecycle_rpc(tree, err)
    if os.path.basename(os.path.dirname(path)) == "log":
        ra03_ok = {i + 1 for i, line in enumerate(src.splitlines())
                   if "ra03-ok" in line}

        def err_ra03(node: ast.AST, code: str, msg: str) -> None:
            if getattr(node, "lineno", 0) not in ra03_ok:
                err(node, code, msg)

        _check_log_io_swallow(tree, err_ra03)
    if os.path.basename(path) in _ENGINE_HOT_FILES:
        ra02_ok = {i + 1 for i, line in enumerate(src.splitlines())
                   if "ra02-ok" in line}

        def err_ra02(node: ast.AST, code: str, msg: str) -> None:
            if getattr(node, "lineno", 0) not in ra02_ok:
                err(node, code, msg)

        _check_engine_hot_sync(tree, err_ra02)
    base = os.path.basename(path)
    parent = os.path.basename(os.path.dirname(path))
    for (b, pdir), roots in _RA10_SCOPES.items():
        if base == b and (pdir is None or parent == pdir):
            ra10_ok = {i + 1 for i, line in enumerate(src.splitlines())
                       if "ra10-ok" in line}

            def err_ra10(node: ast.AST, code: str, msg: str,
                         _ok=ra10_ok) -> None:
                if getattr(node, "lineno", 0) not in _ok:
                    err(node, code, msg)

            _check_classic_hot_path(tree, err_ra10, roots)
    if os.path.basename(path) in _INGRESS_HOT_FILES:
        ra08_ok = {i + 1 for i, line in enumerate(src.splitlines())
                   if "ra08-ok" in line}

        def err_ra08(node: ast.AST, code: str, msg: str) -> None:
            if getattr(node, "lineno", 0) not in ra08_ok:
                err(node, code, msg)

        _check_coalesce_hot_path(tree, err_ra08)
    if os.path.basename(os.path.dirname(path)) == "wire":
        ra09_ok = {i + 1 for i, line in enumerate(src.splitlines())
                   if "ra09-ok" in line}

        def err_ra09(node: ast.AST, code: str, msg: str) -> None:
            if getattr(node, "lineno", 0) not in ra09_ok:
                err(node, code, msg)

        _check_coalesce_hot_path(tree, err_ra09,
                                 roots=_WIRE_SWEEP_FUNCS,
                                 code="RA09", what="wire sweep")
    if os.path.basename(path) in _MESH_FILES:
        # the mesh driver's dispatch loop rides the RA04 no-host-sync
        # closure gate (a sync there serializes the sharded frontier's
        # measured pipeline) and the mesh-side ingress pump path rides
        # RA08's no-per-session-Python gate (ISSUE 11 satellite)
        mesh_lines = src.splitlines()
        ra04_ok_m = {i + 1 for i, line in enumerate(mesh_lines)
                     if "ra04-ok" in line}
        ra08_ok_m = {i + 1 for i, line in enumerate(mesh_lines)
                     if "ra08-ok" in line}

        def err_ra04_mesh(node: ast.AST, code: str, msg: str) -> None:
            if getattr(node, "lineno", 0) not in ra04_ok_m:
                err(node, code, msg)

        def err_ra08_mesh(node: ast.AST, code: str, msg: str) -> None:
            if getattr(node, "lineno", 0) not in ra08_ok_m:
                err(node, code, msg)

        _check_sampler_sync(tree, err_ra04_mesh,
                            roots=_MESH_DISPATCH_FUNCS)
        _check_coalesce_hot_path(tree, err_ra08_mesh,
                                 roots=_MESH_INGRESS_FUNCS)
    if os.path.basename(path) in (_BENCH_FILES | _TELEMETRY_FILES):
        ra04_ok = {i + 1 for i, line in enumerate(src.splitlines())
                   if "ra04-ok" in line}

        def err_ra04(node: ast.AST, code: str, msg: str) -> None:
            if getattr(node, "lineno", 0) not in ra04_ok:
                err(node, code, msg)

        if os.path.basename(path) in _BENCH_FILES:
            _check_bench_loop_sync(tree, err_ra04)
        else:
            _check_sampler_sync(tree, err_ra04)
    if os.path.basename(path) in _BLACKBOX_FILES:
        # the recorder's emit path rides dispatch loops: same RA04
        # no-host-sync closure gate as the sampler tick path
        ra04_ok = {i + 1 for i, line in enumerate(src.splitlines())
                   if "ra04-ok" in line}

        def err_ra04_bb(node: ast.AST, code: str, msg: str) -> None:
            if getattr(node, "lineno", 0) not in ra04_ok:
                err(node, code, msg)

        _check_sampler_sync(tree, err_ra04_bb,
                            roots=_RECORDER_HOT_FUNCS)
        doc = os.path.join(os.path.dirname(path), "docs",
                           "OBSERVABILITY.md")
        if not os.path.exists(doc):
            doc = os.path.join(REPO, "docs", "OBSERVABILITY.md")
        doc_text = None
        if os.path.exists(doc):
            with open(doc, encoding="utf-8") as fdoc:
                doc_text = fdoc.read()
        _check_event_registry_doc(tree, err, doc_text)
    if os.path.basename(path) in _AUTOTUNE_FILES:
        # the controller runs between dispatches: same RA04 closure
        # gate as the sampler tick, rooted at the tuner's tick path
        ra04_ok = {i + 1 for i, line in enumerate(src.splitlines())
                   if "ra04-ok" in line}

        def err_ra04_at(node: ast.AST, code: str, msg: str) -> None:
            if getattr(node, "lineno", 0) not in ra04_ok:
                err(node, code, msg)

        _check_sampler_sync(tree, err_ra04_at, roots=_TUNER_HOT_FUNCS)
        doc = os.path.join(os.path.dirname(path), "docs",
                           "OBSERVABILITY.md")
        if not os.path.exists(doc):
            doc = os.path.join(REPO, "docs", "OBSERVABILITY.md")
        doc_text = None
        if os.path.exists(doc):
            with open(doc, encoding="utf-8") as fdoc:
                doc_text = fdoc.read()
        _check_autotune_contract(tree, err, path, doc_text,
                                 _event_registry_keys(path))
    parts = set(os.path.normpath(path).split(os.sep))
    in_tests = "tests" in parts or \
        os.path.basename(path).startswith("test_")
    if not in_tests:
        keys = _event_registry_keys(path)
        if keys is not None:
            _check_event_registry_use(tree, err, keys)
    if os.path.basename(path) == "metrics.py":
        # the documented-field half of RA05 reads the observability
        # registry doc: prefer one next to the checked file (self-
        # contained fixtures), else the repo's
        doc = os.path.join(os.path.dirname(path), "docs",
                           "OBSERVABILITY.md")
        if not os.path.exists(doc):
            doc = os.path.join(REPO, "docs", "OBSERVABILITY.md")
        doc_text = None
        if os.path.exists(doc):
            with open(doc, encoding="utf-8") as fdoc:
                doc_text = fdoc.read()
        _check_field_registry(tree, err, doc_text)

    # -- F401: unused module-level imports ------------------------------
    if os.path.basename(path) != "__init__.py":
        used = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Name):
                used.add(node.id)
            elif isinstance(node, ast.Attribute):
                pass  # base resolves through a Name anyway
        # names referenced in __all__ strings count as used
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == "__all__":
                        for elt in ast.walk(node.value):
                            if isinstance(elt, ast.Constant) and \
                                    isinstance(elt.value, str):
                                used.add(elt.value)
        for node in tree.body:
            if isinstance(node, ast.ImportFrom) and \
                    node.module == "__future__":
                continue
            if isinstance(node, ast.Import):
                for alias in node.names:
                    bound = (alias.asname or
                             alias.name.split(".")[0])
                    if bound not in used:
                        err(node, "F401",
                            f"'{alias.name}' imported but unused")
            elif isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    bound = alias.asname or alias.name
                    if bound not in used:
                        err(node, "F401",
                            f"'{alias.name}' imported but unused")

    for node in ast.walk(tree):
        # -- B006 mutable defaults --------------------------------------
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for default in list(node.args.defaults) + \
                    [d for d in node.args.kw_defaults if d is not None]:
                if _is_mutable_default(default):
                    err(default, "B006",
                        f"mutable default argument in {node.name}()")
        # -- E722 bare except -------------------------------------------
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            err(node, "E722", "bare 'except:'")
        # -- F631 assert on tuple ---------------------------------------
        if isinstance(node, ast.Assert) and \
                isinstance(node.test, ast.Tuple) and node.test.elts:
            err(node, "F631", "assert on a non-empty tuple is always true")
        # -- F632 is-literal --------------------------------------------
        if isinstance(node, ast.Compare):
            for op, comp in zip(node.ops, node.comparators):
                if isinstance(op, (ast.Is, ast.IsNot)) and \
                        isinstance(comp, ast.Constant) and \
                        isinstance(comp.value, (str, int, float, bytes)) \
                        and not isinstance(comp.value, bool):
                    err(node, "F632",
                        "'is' comparison with a literal; use ==")
        # -- F541 placeholder-less f-string -----------------------------
        if isinstance(node, ast.JoinedStr) and id(node) not in spec_ids \
                and not any(isinstance(v, ast.FormattedValue)
                            for v in node.values):
            err(node, "F541", "f-string without placeholders")
        # -- F601 duplicate dict keys -----------------------------------
        if isinstance(node, ast.Dict):
            seen: set = set()
            for key in node.keys:
                if isinstance(key, ast.Constant):
                    try:
                        if key.value in seen:
                            err(key, "F601",
                                f"duplicate dict key {key.value!r}")
                        seen.add(key.value)
                    except TypeError:
                        pass
        # -- F811 redefinition in one scope + W101 unreachable ----------
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = node.body
            defs: dict = {}
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.ClassDef)):
                    decs = getattr(stmt, "decorator_list", [])
                    if any(_decorator_exempts_redef(d) for d in decs):
                        continue
                    if stmt.name in defs:
                        err(stmt, "F811",
                            f"redefinition of '{stmt.name}' "
                            f"(first at line {defs[stmt.name]})")
                    defs[stmt.name] = stmt.lineno
        for field in ("body", "orelse", "finalbody"):
            body = getattr(node, field, None)
            if isinstance(body, list):
                for i, stmt in enumerate(body[:-1]):
                    if isinstance(stmt, _TERMINAL):
                        err(body[i + 1], "W101",
                            "unreachable code after "
                            f"{type(stmt).__name__.lower()}")
                        break
    return errors


def main(argv: list) -> int:
    targets = argv or DEFAULT_TARGETS
    files: list = []
    for t in targets:
        p = os.path.join(REPO, t) if not os.path.isabs(t) else t
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".pytest_cache")]
                files += [os.path.join(root, n) for n in names
                          if n.endswith(".py")]
        elif p.endswith(".py"):
            files.append(p)
    errors: list = []
    for f in sorted(files):
        errors += check_file(f)
    for e in errors:
        print(e)
    print(f"lint: {len(files)} files, {len(errors)} findings")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
