"""bench_diff — compare two bench JSON tails and flag regressions.

The bench evidence (BENCH_r*.json history, bench.py child tails,
bench_classic tails) is only useful if rounds are actually COMPARED;
until now that comparison was a human eyeballing JSON.  This tool
makes it mechanical: feed it two bench documents (old first) and it
extracts every comparable row — a dict carrying ``value`` plus
optional latency percentiles, found at the top level or nested under
``detail`` — pairs rows by name, and flags:

* **throughput regressions**: ``value`` dropped by more than the
  noise bar (throughput is higher-is-better);
* **latency regressions**: ``p99_commit_latency_ms`` /
  ``p50_commit_latency_ms`` / ``p99_applied_latency_ms`` rose by more
  than the bar (lower-is-better; -1 sentinels = not measured, skipped);
* frontier ``points`` are compared per ``cmds_per_step``;
* classic captures (BENCH_CLASSIC_r*, ISSUE 13) are compared per
  phase: ``classic/local`` and ``classic/tcp`` rows pair the
  ``classic_node_committed_cmds_per_sec`` sub-values (higher-better)
  and their ``p99_applied_latency_ms`` (lower-better), so the classic
  frontier is regression-tracked like every other;
* multichip sweep tails (ISSUE 11) are compared per mesh shape x lane
  rung (``multichip/<mesh>/lanes<N>``, cmds_per_s higher-is-better) —
  a cross-round mesh delta is attributable via each row's stamped
  ``engine_pipeline`` config (superstep_k/dispatch_ahead/donation/
  wal shard layout/mesh shape);
* **device-plane regressions** (ISSUE 16): ``n_compiles`` /
  ``n_recompiles`` are compile COUNTS at a fixed workload, so they are
  compared absolutely — ANY growth flags, no noise bar (a retrace
  regression compiles once per shape variant, which can hide inside a
  10% bar); ``compile_time_s`` / ``transfer_bytes_per_cmd`` /
  ``peak_live_bytes`` are lower-is-better with 0 a meaningful healthy
  baseline (the classic tail stamps zeros), so they ride the shed-rate
  comparison shape.

The noise bar defaults to 10% — the builder-box numbers swing with
host load (the BENCH_r02 vs r04 host-drift note), so a tight default
would page on weather.  Cross-host comparisons are labelled: the tool
prints both ``host`` stamps when they differ, since a regression
verdict across different machines is evidence, not proof.

Usage:
    python tools/bench_diff.py OLD.json NEW.json [--noise-pct 10]
                               [--json]

Prints a human summary (or one JSON line with ``--json``) and exits
1 when any regression was flagged, 0 otherwise — wired into
tests/test_bench_paths.py so the tail format cannot drift out from
under it.
"""
from __future__ import annotations

import json
import sys

#: lower-is-better latency fields compared when present in both rows.
#: ``read_p99_ms`` (ISSUE 20) is the read plane's submit→serve p99 —
#: it rides the same shape (-1 "no reads ran" sentinel skipped)
LATENCY_FIELDS = ("p50_commit_latency_ms", "p99_commit_latency_ms",
                  "p50_applied_latency_ms", "p99_applied_latency_ms",
                  "read_p99_ms")

#: ingress-plane keys (ISSUE 10), compared when BOTH tails carry them:
#: throughput is higher-is-better like ``value``; shed rate is
#: lower-is-better AND zero is a meaningful healthy baseline, so a
#: shed rate appearing from 0 flags against an absolute floor of 1.0
#: in the relative formula rather than being skipped as degenerate
#: wire-plane keys (ISSUE 12) ride the same two shapes: throughput
#: higher-is-better; shed rate AND reconnect-storm recovery time
#: lower-is-better with 0 a meaningful healthy baseline (recovery
#: carries a -1 "no storm ran" sentinel, skipped like the latency
#: sentinels)
#: placement-failover keys (ISSUE 17) ride the shed shape too:
#: ``failover_recovery_s`` (kill-9 → first commit on the new home)
#: lower-is-better with a -1 "no failover ran" sentinel, and
#: ``failover_lost_acked`` lower-is-better where 0 is THE healthy
#: baseline — any acked-but-lost delta appearing from 0 must flag
#: read-plane throughput (ISSUE 20): served consistent reads per
#: second through the vectorized lease/read-index path — higher-better
#: like the write rates it rides next to
INGRESS_RATE_FIELDS = ("ingress_cmds_per_s", "wire_cmds_per_s",
                       "read_cmds_per_s")
#: ``encode_share_pct`` (ISSUE 18) rides the shed shape as well: the
#: codec's encode phase share of total phase time — lower-better,
#: 0 a meaningful healthy value (everything arrived pre-encoded), and
#: -1 the "no phase samples" sentinel skipped like the others
#: geo-soak keys (ISSUE 19) ride the same shape:
#: ``geo_failover_recovery_s`` (SIGKILL → first commit on the new
#: home, across real processes + the latency matrix) lower-is-better,
#: and ``geo_false_migrations`` lower-is-better where 0 is THE healthy
#: baseline — any migration during a delay-only episode must flag
#: read-plane shed/stale keys (ISSUE 20) ride the shed shape: both
#: lower-better with 0 THE healthy baseline — a stale-refusal count
#: appearing from 0 under the same workload must flag
INGRESS_SHED_FIELDS = ("ingress_shed_rate", "wire_shed_rate",
                       "wire_reconnect_recovery_s",
                       "failover_recovery_s", "failover_lost_acked",
                       "encode_share_pct",
                       "geo_failover_recovery_s",
                       "geo_false_migrations",
                       "read_shed_rate", "read_stale_refused")

#: device-plane compile counts (ISSUE 16): absolute comparison, any
#: growth is a regression — the workload is fixed across rounds, so an
#: extra compile means an extra traced shape variant, not noise
DEVICE_COUNT_FIELDS = ("n_compiles", "n_recompiles")
#: device-plane costs: lower-is-better, 0 = healthy baseline (classic
#: tails stamp zeros), so the shed-rate absolute-floor shape applies
DEVICE_COST_FIELDS = ("compile_time_s", "transfer_bytes_per_cmd",
                      "peak_live_bytes")


def _is_row(d) -> bool:
    return isinstance(d, dict) and isinstance(d.get("value"), (int, float))


def extract_rows(doc: dict) -> dict:
    """name -> comparable row.  A bench child tail is one row
    (``headline``); a parent/BENCH_r*.json doc contributes its
    top-level value plus every row-shaped entry under ``detail``;
    frontier docs additionally expand ``points`` per cmds_per_step."""
    rows: dict = {}

    def add(name: str, row: dict) -> None:
        if _is_row(row):
            rows[name] = row
        for i, p in enumerate(row.get("points") or []):
            if _is_row(p):
                rows[f"{name}/cmds{p.get('cmds_per_step', i)}"] = p

    if _is_row(doc):
        add("headline", doc)
    for i, m in enumerate(doc.get("multichip") or []):
        # multichip sweep rows, one per mesh shape x lane rung; the
        # dryrun-format rows carry ``cmds_per_s`` instead of ``value``
        if not isinstance(m, dict):
            continue
        row = dict(m)
        if "value" not in row and \
                isinstance(row.get("cmds_per_s"), (int, float)):
            row["value"] = row["cmds_per_s"]
        if _is_row(row):
            rows[f"multichip/{row.get('mesh', i)}/"
                 f"lanes{row.get('lanes', '?')}"] = row
    detail = doc.get("detail")
    classic = doc.get("metric") == "classic_node_committed_cmds_per_sec"
    if isinstance(detail, dict):
        for key, sub in detail.items():
            if _is_row(sub):
                # classic phase rows get a stable namespaced name so
                # r05-era and r06-era captures pair up (ISSUE 13)
                add(f"classic/{key}" if classic and
                    key in ("local", "tcp") else key, sub)
    return rows


def compare_rows(old: dict, new: dict, noise_pct: float) -> list:
    """Per-metric comparison of one row pair -> finding dicts."""
    bar = noise_pct / 100.0
    out = []
    ov, nv = float(old["value"]), float(new["value"])
    if ov > 0:
        delta = (nv - ov) / ov
        out.append({"metric": "value", "old": ov, "new": nv,
                    "delta_pct": round(100 * delta, 2),
                    "regression": delta < -bar})
    for f in LATENCY_FIELDS:
        o, n = old.get(f), new.get(f)
        if not isinstance(o, (int, float)) or \
                not isinstance(n, (int, float)) or o <= 0 or n <= 0:
            continue  # -1 = never measured; 0 = degenerate sample
        delta = (n - o) / o
        out.append({"metric": f, "old": o, "new": n,
                    "delta_pct": round(100 * delta, 2),
                    "regression": delta > bar})
    for f in INGRESS_RATE_FIELDS:
        o, n = old.get(f), new.get(f)
        if not isinstance(o, (int, float)) or \
                not isinstance(n, (int, float)) or o <= 0:
            continue
        delta = (n - o) / o
        out.append({"metric": f, "old": o, "new": n,
                    "delta_pct": round(100 * delta, 2),
                    "regression": delta < -bar})
    for f in INGRESS_SHED_FIELDS:
        o, n = old.get(f), new.get(f)
        if not isinstance(o, (int, float)) or \
                not isinstance(n, (int, float)) or o < 0 or n < 0:
            continue  # negative = sentinel; 0 is a real (healthy) rate
        base = o if o > 0 else 1.0
        delta = (n - o) / base
        out.append({"metric": f, "old": o, "new": n,
                    "delta_pct": round(100 * delta, 2),
                    "regression": delta > bar})
    for f in DEVICE_COUNT_FIELDS:
        o, n = old.get(f), new.get(f)
        if not isinstance(o, (int, float)) or \
                not isinstance(n, (int, float)) or o < 0 or n < 0:
            continue
        base = o if o > 0 else 1.0
        out.append({"metric": f, "old": o, "new": n,
                    "delta_pct": round(100 * (n - o) / base, 2),
                    "regression": n > o})  # absolute: no noise bar
    for f in DEVICE_COST_FIELDS:
        o, n = old.get(f), new.get(f)
        if not isinstance(o, (int, float)) or \
                not isinstance(n, (int, float)) or o < 0 or n < 0:
            continue  # negative = sentinel; 0 is a real healthy value
        base = o if o > 0 else 1.0
        delta = (n - o) / base
        out.append({"metric": f, "old": o, "new": n,
                    "delta_pct": round(100 * delta, 2),
                    "regression": delta > bar})
    return out


def diff(old_doc: dict, new_doc: dict, noise_pct: float = 10.0) -> dict:
    old_rows = extract_rows(old_doc)
    new_rows = extract_rows(new_doc)
    rows: dict = {}
    regressions = 0
    for name in sorted(set(old_rows) & set(new_rows)):
        findings = compare_rows(old_rows[name], new_rows[name],
                                noise_pct)
        rows[name] = findings
        regressions += sum(1 for f in findings if f["regression"])
    hosts = (old_doc.get("host") or
             (old_doc.get("detail") or {}).get("host"),
             new_doc.get("host") or
             (new_doc.get("detail") or {}).get("host"))
    cross_host = (hosts[0] or {}).get("hostname") != \
        (hosts[1] or {}).get("hostname") if all(hosts) else False
    return {
        "noise_pct": noise_pct,
        "rows_compared": len(rows),
        "rows_only_old": sorted(set(old_rows) - set(new_rows)),
        "rows_only_new": sorted(set(new_rows) - set(old_rows)),
        "regressions": regressions,
        "cross_host": cross_host,
        "rows": rows,
    }


def _render(result: dict) -> str:
    lines = [f"bench_diff  rows={result['rows_compared']} "
             f"noise_bar={result['noise_pct']:g}% "
             f"regressions={result['regressions']}"]
    if result["cross_host"]:
        lines.append("NOTE    different hosts — verdicts are "
                     "evidence, not proof")
    for name, findings in result["rows"].items():
        for f in findings:
            flag = " <<< REGRESSION" if f["regression"] else ""
            lines.append(
                f"{name:24s} {f['metric']:24s} "
                f"{f['old']:>12g} -> {f['new']:>12g}  "
                f"{f['delta_pct']:+.1f}%{flag}")
    for name in result["rows_only_old"]:
        lines.append(f"{name:24s} only in OLD (row dropped?)")
    for name in result["rows_only_new"]:
        lines.append(f"{name:24s} only in NEW")
    return "\n".join(lines)


def _load(path: str) -> dict:
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except ValueError:
        # a bench stdout capture: take the last parsable JSON line
        doc = None
        for line in reversed(text.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                doc = json.loads(line)
                break
        if doc is None:
            raise
    # the BENCH_r*.json round history wraps the bench doc in a capture
    # record ({"cmd", "rc", "tail", "parsed"}); unwrap it — falling
    # back to re-parsing the raw tail when the capture's own parse was
    # None (a truncated tail yields zero comparable rows, not a crash)
    if isinstance(doc, dict) and "parsed" in doc and "value" not in doc:
        if isinstance(doc["parsed"], dict):
            doc = doc["parsed"]
        else:
            try:
                doc = json.loads(doc.get("tail") or "")
            except ValueError:
                pass
    return doc


def main(argv: list) -> int:
    as_json = "--json" in argv
    noise = 10.0
    paths: list = []
    it = iter(argv)
    for a in it:
        if a == "--noise-pct":
            noise = float(next(it, "10"))
        elif a == "--json":
            continue
        elif not a.startswith("--"):
            paths.append(a)
    if len(paths) != 2:
        print("usage: bench_diff.py OLD.json NEW.json "
              "[--noise-pct P] [--json]", file=sys.stderr)
        return 2
    result = diff(_load(paths[0]), _load(paths[1]), noise)
    print(json.dumps(result) if as_json else _render(result))
    return 1 if result["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
