"""ra_trace — reconstruct per-command timelines from flight-recorder
bundles (ISSUE 7: the *why was THIS command slow* tool).

Input: one or more post-mortem bundles (``ra_tpu.blackbox`` dumps) or
raw event JSONL files (one ``[ts, etype, fields]`` line each).  Multiple
bundles merge into one timeline — classic TCP nodes dump one bundle per
process, and each appears as its own ``pid`` in the Chrome export via
the trace context that crossed the wire.

Joins (the causal model, docs/INTERNALS.md §10):

* events carrying an explicit ``trace`` field (cmd.*, rpc.*) group
  directly by trace id;
* WAL-plane events are ``(uid, idx)``-keyed: a trace's ``cmd.append``
  names ``(uid, idx)``, and the covering ``wal.write`` /
  ``wal.confirm`` ranges plus the first ``cmd.commit`` advance at or
  past idx complete the lifecycle;
* engine-plane events are ``(lane, submit_index)``-keyed:
  ``engine.submit`` step ranges pair with per-shard ``engine.confirm``
  horizons (``--steps``); joining those against on-device step stamps
  is the bench's job (``latency_mode: step_stamped``), not the host's.
* fault events (``disk.fault`` / ``net.fault`` / ``wal.poison`` /
  ``wal.kill``) inside a command's time window attach to its timeline
  — the injected fault is visible next to the hop it delayed.

Usage:
    python tools/ra_trace.py BUNDLE [BUNDLE...] [--list]
    python tools/ra_trace.py BUNDLE --explain TRACE_ID
    python tools/ra_trace.py BUNDLE --explain auto
    python tools/ra_trace.py BUNDLE --out trace.json   # chrome://tracing
    python tools/ra_trace.py BUNDLE --steps            # engine step lat
"""
from __future__ import annotations

import json
import sys

#: event types that carry an explicit trace id
_FAULT_TYPES = ("disk.fault", "net.fault", "wal.poison", "wal.kill",
                "wal.escalate", "wal.resend")

#: lifecycle order used for hop labelling (ties broken by timestamp)
_HOP_ORDER = ("cmd.ingress", "rpc.send", "cmd.submit", "rpc.recv",
              "rpc.dup", "cmd.append", "wal.fsync", "wal.write",
              "wal.confirm", "cmd.commit", "cmd.apply")


def load_events(paths: list) -> list:
    """-> [(ts, etype, fields, origin)] merged + time-sorted from
    bundles (ra-tpu-blackbox-1 JSON) and/or raw event JSONL files."""
    out: list = []
    for path in paths:
        if path.endswith(".jsonl"):
            with open(path) as f:
                for raw in f:
                    raw = raw.strip()
                    if not raw:
                        continue
                    try:
                        ts, etype, fields = json.loads(raw)
                    except ValueError:
                        continue  # torn tail mid-append
                    out.append((ts, etype, fields, path))
            continue
        with open(path) as f:
            doc = json.load(f)
        if doc.get("format") != "ra-tpu-blackbox-1":
            raise ValueError(f"not a blackbox bundle: {path}")
        origin = doc.get("origin", path)
        for _sub, evts in doc.get("events", {}).items():
            for ts, etype, fields in evts:
                out.append((ts, etype, fields, origin))
    out.sort(key=lambda e: e[0])
    return out


def index_traces(events: list) -> dict:
    """Group events into per-command timelines.

    -> {trace_id: {"hops": [(ts, etype, fields, origin)],
                   "uid": str|None, "idx": int|None,
                   "faults": [(ts, etype, fields, origin)]}}
    Direct hops come from the ``trace`` field; WAL hops join through
    the (uid, idx) the trace's cmd.append names."""
    traces: dict = {}
    for ev in events:
        tr = ev[2].get("trace")
        if tr:
            traces.setdefault(tr, {"hops": [], "uid": None,
                                   "idx": None, "faults": []})
            traces[tr]["hops"].append(ev)
    for tl in traces.values():
        app = next((e for e in tl["hops"] if e[1] == "cmd.append"), None)
        if app is None:
            continue
        uid, idx, t_app = app[2].get("uid"), app[2].get("idx"), app[0]
        tl["uid"], tl["idx"] = uid, idx
        confirm_ts = None
        fsyncs: list = []
        for ev in events:
            ts, etype, fields, _o = ev
            if ts < t_app:
                continue
            if etype == "wal.write":
                rng = (fields.get("ranges") or {}).get(uid)
                if rng and rng[0] <= idx <= rng[1] \
                        and confirm_ts is None:
                    tl["hops"].append(ev)
            elif etype == "wal.confirm" and fields.get("uid") == uid \
                    and fields.get("lo", 1) <= idx <= fields.get("hi", 0):
                if confirm_ts is None:
                    confirm_ts = ts
                    tl["hops"].append(ev)
                    if fsyncs:
                        # the batch's durability syscall: the last sync
                        # before this entry's confirm
                        tl["hops"].append(fsyncs[-1])
            elif etype == "wal.fsync" and confirm_ts is None:
                fsyncs.append(ev)
            elif etype == "cmd.commit" and fields.get("uid") == uid \
                    and fields.get("idx", -1) >= idx:
                tl["hops"].append(ev)
                break
    # attach fault events falling inside each trace's window
    for tl in traces.values():
        if not tl["hops"]:
            continue
        tl["hops"].sort(key=lambda e: e[0])
        t0, t1 = tl["hops"][0][0], tl["hops"][-1][0]
        tl["faults"] = [e for e in events
                        if e[1] in _FAULT_TYPES and t0 <= e[0] <= t1]
    return traces


def completeness(tl: dict) -> set:
    return {e[1] for e in tl["hops"]}


def pick_auto(traces: dict) -> str | None:
    """The trace worth explaining unprompted: most complete lifecycle,
    faulted ones first (the post-mortem question is 'show me a command
    the fault touched')."""
    best, best_key = None, (-1, -1)
    for tid, tl in traces.items():
        key = (len(tl["faults"]) > 0, len(completeness(tl)))
        if key > best_key:
            best, best_key = tid, key
    return best


def explain(trace_id: str, tl: dict) -> str:
    """Hop-by-hop latency breakdown of one command's lifecycle."""
    hops = sorted(tl["hops"], key=lambda e: e[0])
    if not hops:
        return f"trace {trace_id}: no events"
    t0 = hops[0][0]
    lines = [f"trace {trace_id}"
             + (f"  (uid={tl['uid']}, idx={tl['idx']})"
                if tl["uid"] else "")]
    by_type: dict = {}
    for ts, etype, fields, origin in hops:
        by_type.setdefault(etype, ts)
        detail = " ".join(
            f"{k}={v}" for k, v in fields.items()
            if k not in ("trace", "ranges") and not isinstance(v, dict))
        lines.append(f"  +{(ts - t0) * 1000:9.3f}ms  {etype:<12} "
                     f"{detail[:80]}  [{origin}]")
    for ts, etype, fields, _o in sorted(tl["faults"],
                                        key=lambda e: e[0]):
        detail = " ".join(f"{k}={v}" for k, v in fields.items()
                          if not isinstance(v, dict))
        lines.append(f"  +{(ts - t0) * 1000:9.3f}ms  FAULT {etype:<12} "
                     f"{detail[:74]}")

    def dt(a: str, b: str):
        if a in by_type and b in by_type:
            return (by_type[b] - by_type[a]) * 1000
        return None

    parts = []
    for label, a, b in (
            ("client queue/redirect", "cmd.ingress", "cmd.submit"),
            ("deliver+append", "cmd.submit", "cmd.append"),
            ("wal write+fsync wait", "cmd.append", "wal.confirm"),
            ("commit lag", "wal.confirm", "cmd.commit"),
            ("commit lag", "cmd.append", "cmd.commit"),
            ("apply", "cmd.commit", "cmd.apply")):
        d = dt(a, b)
        if d is not None and not any(p[0] == label for p in parts):
            parts.append((label, d))
    if parts:
        lines.append("  breakdown: " + "  |  ".join(
            f"{label} {d:.3f}ms" for label, d in parts))
    if tl["faults"]:
        kinds = sorted({e[2].get("kind", e[1]) for e in tl["faults"]})
        lines.append(f"  faults in window: {', '.join(kinds)}")
    return "\n".join(lines)


def step_latencies(events: list) -> list:
    """Engine-plane (submit_index)-join: pair engine.submit step ranges
    with per-shard engine.confirm horizons -> [(step, submit_ts,
    {shard: confirm_ts})].  Lane attribution within a step comes from
    the on-device step stamps (INTERNALS §10), not host events."""
    submits: dict = {}
    for ts, etype, fields, _o in events:
        if etype == "engine.submit":
            for s in range(fields.get("step_lo", 0),
                           fields.get("step_hi", -1) + 1):
                submits.setdefault(s, [ts, {}])
        elif etype == "engine.confirm":
            sh = fields.get("shard", 0)
            hi = fields.get("step", 0)
            for s, rec in submits.items():
                if s <= hi and sh not in rec[1]:
                    rec[1][sh] = ts
    return sorted((s, rec[0], rec[1]) for s, rec in submits.items())


def to_chrome(events: list, traces: dict, out_path: str) -> str:
    """Chrome trace-event JSON: every origin (process/bundle) is a
    ``pid``, subsystems are ``tid``s, traced commands add one span row
    per hop pair (load in chrome://tracing or ui.perfetto.dev)."""
    if not events:
        raise ValueError("no events to export")
    t0 = events[0][0]
    pids: dict = {}
    tids: dict = {}
    doc: list = []

    def pid_of(origin: str) -> int:
        if origin not in pids:
            pids[origin] = len(pids) + 1
            doc.append({"ph": "M", "name": "process_name",
                        "pid": pids[origin], "tid": 0,
                        "args": {"name": origin}})
        return pids[origin]

    def tid_of(sub: str) -> int:
        return tids.setdefault(sub, len(tids) + 1)

    for ts, etype, fields, origin in events:
        doc.append({"ph": "i", "s": "t", "name": etype,
                    "cat": etype.partition(".")[0],
                    "ts": (ts - t0) * 1e6,
                    "pid": pid_of(origin),
                    "tid": tid_of(etype.partition(".")[0]),
                    "args": {k: v for k, v in fields.items()
                             if not isinstance(v, dict)}})
    row = 1000
    for tid_name, tl in sorted(traces.items()):
        hops = sorted(tl["hops"], key=lambda e: e[0])
        if len(hops) < 2:
            continue
        row += 1
        doc.append({"ph": "M", "name": "thread_name", "pid": 0,
                    "tid": row, "args": {"name": f"trace {tid_name}"}})
        for a, b in zip(hops, hops[1:]):
            doc.append({"ph": "X", "name": f"{a[1]} -> {b[1]}",
                        "cat": "trace",
                        "ts": (a[0] - t0) * 1e6,
                        "dur": max((b[0] - a[0]) * 1e6, 0.1),
                        "pid": 0, "tid": row,
                        "args": {"trace": tid_name}})
    with open(out_path, "w") as f:
        json.dump({"traceEvents": doc, "displayTimeUnit": "ms"}, f)
    return out_path


def main(argv: list) -> int:
    paths, out, explain_id = [], None, None
    list_only = steps = False
    it = iter(argv)
    for a in it:
        if a == "--out":
            out = next(it, "trace.json")
        elif a == "--explain":
            explain_id = next(it, "auto")
        elif a == "--list":
            list_only = True
        elif a == "--steps":
            steps = True
        elif not a.startswith("--"):
            paths.append(a)
    if not paths:
        print(__doc__)
        return 2
    events = load_events(paths)
    traces = index_traces(events)
    if not (out or explain_id or steps) or list_only:
        print(f"{len(events)} events, {len(traces)} traced commands")
        for tid, tl in sorted(traces.items()):
            hops = sorted(completeness(tl))
            flag = "  FAULTED" if tl["faults"] else ""
            print(f"  {tid:<24} {len(tl['hops'])} hops "
                  f"[{', '.join(hops)}]{flag}")
    if steps:
        rows = step_latencies(events)
        print(f"{len(rows)} engine steps (submit -> per-shard confirm)")
        for s, sub_ts, confirms in rows[-16:]:
            lat = " ".join(
                f"s{sh}:{(ts - sub_ts) * 1000:.2f}ms"
                for sh, ts in sorted(confirms.items())) or "unconfirmed"
            print(f"  step {s:<8} {lat}")
    if explain_id is not None:
        tid = pick_auto(traces) if explain_id == "auto" else explain_id
        if tid is None or tid not in traces:
            print(f"ra_trace: no such trace {explain_id!r} "
                  f"({len(traces)} known; --list to see them)")
            return 1
        print(explain(tid, traces[tid]))
    if out:
        print(f"wrote {to_chrome(events, traces, out)}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
