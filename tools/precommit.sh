#!/bin/sh
# Pre-commit gate (ISSUE 15 satellite): the fast local loop.
#
#   tools/precommit.sh            # lint what changed + the gate tests
#
# 1. `tools/lint.py --changed` lints only files differing from HEAD
#    (staged, unstaged, untracked) — the whole-program engine still
#    indexes the full tree, so cross-module closures and allowlist
#    tags resolve exactly as in the full run; only REPORTING is scoped.
# 2. `tools/soak.py --device-obs 0 1` runs ONE seed of the ISSUE 16
#    device-observatory chaos episode (~4s): the recompile sentinel
#    stays quiet under election/disk chaos and the deliberate
#    mixed-shape probe is detected — the runtime mirror of the jit
#    static gates, so a retrace regression fails the same local loop
#    that catches a lint finding.
# 3. `tools/soak.py --failover 0` runs ONE seed of the ISSUE 17
#    placement-failover soak (~10s): a lane engine kill-9'd
#    mid-traffic, the classic control plane commits the re-placement,
#    sessions re-home, and the exactly-once oracle closes over the
#    union of both engines' state.
# 4. `tools/soak.py --geo 0` runs ONE seed of the ISSUE 19
#    geo-distributed survival soak: control quorum + two engine hosts
#    as separate processes behind a latency-domain matrix, a
#    delay-only episode that must migrate nothing, then a SIGKILL
#    failover over the reliable RPC tier with the exactly-once oracle
#    read back over RPC.
# 5. `tools/soak.py --reads 0 1` runs ONE seed of the ISSUE 20
#    linearizable-read oracle (~8s): both read machines, single-device
#    and sharded mesh plus a durable disk-fault run, with every served
#    consistent read checked against the host model fold across
#    election churn / leader kills / majority partitions (stale serves
#    pinned 0, lease reads never outlive expiry).
# 6. `pytest tests/test_static_gates.py` runs the full gate suite
#    (rule fixtures + clean pins + the analyzer runtime budget).
#
# Exit nonzero on any finding or test failure.  The full-tree lint
# (`python tools/lint.py`, ~8s) is what CI runs; this script is the
# subset worth paying before every commit.
set -e
cd "$(dirname "$0")/.."
python tools/lint.py --changed
python tools/soak.py --device-obs 0 1
python tools/soak.py --failover 0
python tools/soak.py --geo 0
python tools/soak.py --reads 0 1
exec python -m pytest tests/test_static_gates.py -q
